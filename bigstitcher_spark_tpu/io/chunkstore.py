"""Chunked-array storage layer: N5, ZARR (v2 / OME-ZARR), HDF5.

TPU-native replacement for the reference's L1 (n5/n5-zarr/n5-hdf5 writers,
util/N5Util.java:45-105): tensorstore does the chunk IO (async, C codecs),
h5py covers HDF5 (local-only, same restriction as the reference's
CreateFusionContainer.java:141-145).

All public APIs use **xyz-first logical axis order** (N5/imglib2 convention —
first axis fastest). For the zarr driver, whose on-disk shape is C-order
(e.g. OME-NGFF ``[t,c,z,y,x]``), the wrapper reverses axes at the boundary so
callers never see driver-specific order. Group attributes are plain JSON files
(``attributes.json`` / ``.zattrs``) manipulated directly, with N5-style nested
key paths (``setAttribute("/", "a/b", v)`` -> ``{"a": {"b": v}}``).
"""

from __future__ import annotations

import enum
import json
import os
import shutil
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np
import tensorstore as ts


class StorageFormat(str, enum.Enum):
    N5 = "N5"
    ZARR = "ZARR"
    HDF5 = "HDF5"


_N5_DTYPES = {
    "uint8", "uint16", "uint32", "uint64",
    "int8", "int16", "int32", "int64",
    "float32", "float64",
}

_ZARR_DTYPE = {
    "uint8": "|u1", "uint16": "<u2", "uint32": "<u4", "uint64": "<u8",
    "int8": "|i1", "int16": "<i2", "int32": "<i4", "int64": "<i8",
    "float32": "<f4", "float64": "<f8",
}


def _n5_compression(name: str) -> dict:
    name = name.lower()
    if name == "zstd":
        return {"type": "zstd"}
    if name == "gzip":
        return {"type": "gzip"}
    if name == "raw":
        return {"type": "raw"}
    if name == "blosc":
        return {"type": "blosc", "cname": "zstd", "clevel": 3, "shuffle": 1}
    raise ValueError(f"unsupported n5 compression: {name}")


def _zarr_compressor(name: str) -> dict | None:
    name = name.lower()
    if name == "zstd":
        return {"id": "zstd", "level": 3}
    if name == "gzip":
        return {"id": "zlib", "level": 5}
    if name == "blosc":
        return {"id": "blosc", "cname": "zstd", "clevel": 3, "shuffle": 1}
    if name == "raw":
        return None
    raise ValueError(f"unsupported zarr compression: {name}")


@dataclass
class Dataset:
    """A chunked array presented in xyz-first logical order."""

    store: "ChunkStore"
    path: str
    _ts: Any  # tensorstore.TensorStore or h5py.Dataset
    reversed_axes: bool  # True when on-disk order is C (zarr/hdf5)

    @property
    def shape(self) -> tuple[int, ...]:
        s = tuple(int(v) for v in self._ts.shape)
        return s[::-1] if self.reversed_axes else s

    @property
    def block_size(self) -> tuple[int, ...]:
        if hasattr(self._ts, "chunk_layout"):
            c = self._ts.chunk_layout.read_chunk.shape
        else:  # h5py
            c = self._ts.chunks
        c = tuple(int(v) for v in c)
        return c[::-1] if self.reversed_axes else c

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self._ts.dtype.numpy_dtype if hasattr(self._ts.dtype, "numpy_dtype") else self._ts.dtype)

    def _sel(self, offset: Sequence[int], shape: Sequence[int]):
        idx = tuple(slice(int(o), int(o) + int(s)) for o, s in zip(offset, shape))
        return idx[::-1] if self.reversed_axes else idx

    def read(self, offset: Sequence[int], shape: Sequence[int]) -> np.ndarray:
        """Read a box (xyz-first offset/shape) into a numpy array (xyz-first)."""
        sel = self._sel(offset, shape)
        if hasattr(self._ts, "read"):
            data = self._ts[sel].read().result()
        else:
            data = self._ts[sel]
        data = np.asarray(data)
        return data.transpose(tuple(range(data.ndim))[::-1]) if self.reversed_axes else data

    def write(self, data: np.ndarray, offset: Sequence[int]) -> None:
        """Write a numpy array (xyz-first) at an xyz-first offset.

        Block-aligned N5 writes take the native codec fast path (GIL-free
        zstd encode + file write, io.native_blockio) when available."""
        if self._native_write(data, offset):
            return
        sel = self._sel(offset, data.shape)
        if self.reversed_axes:
            data = data.transpose(tuple(range(data.ndim))[::-1])
        if hasattr(self._ts, "read"):
            self._ts[sel].write(np.ascontiguousarray(data)).result()
        else:
            self._ts[sel] = data

    def _native_write(self, data: np.ndarray, offset: Sequence[int]) -> bool:
        """N5 + zstd/raw + block-aligned box -> write chunk files natively.
        Returns False when ineligible (caller falls back to tensorstore)."""
        if (self.reversed_axes or self.store is None
                or getattr(self.store, "format", None) != StorageFormat.N5
                or os.environ.get("BST_NATIVE_IO", "1") != "1"):
            return False
        comp = (self.store.get_attribute(self.path, "compression", {}) or {})
        ctype = comp.get("type", "zstd")
        if ctype not in ("zstd", "raw"):
            return False
        from . import native_blockio

        if not native_blockio.available():
            return False
        block = self.block_size
        dims = self.shape
        if data.dtype != self.dtype:
            return False
        for d in range(data.ndim):
            o, s = int(offset[d]), int(data.shape[d])
            if o % block[d] != 0:
                return False
            if s != min(block[d], dims[d] - o):
                return False  # must be exactly one full (or edge) block span
        # the box may span one block only (writers are block-aligned and
        # compute blocks are handled by callers splitting per storage block)
        if any(int(data.shape[d]) > block[d] for d in range(data.ndim)):
            grid = [range(0, int(data.shape[d]), block[d])
                    for d in range(data.ndim)]
            import itertools

            for corner in itertools.product(*grid):
                sub = data[tuple(slice(c, min(c + block[d], data.shape[d]))
                                 for d, c in enumerate(corner))]
                off = [int(offset[d]) + c for d, c in enumerate(corner)]
                if not self._native_write(sub, off):
                    return False
            return True
        pos = [int(offset[d]) // block[d] for d in range(data.ndim)]
        path = os.path.join(self.store._kvpath(self.path),
                            *[str(p) for p in pos])
        level = int(comp.get("level", 3)) or 3
        native_blockio.write_block(path, data, compression=ctype, level=level)
        return True

    def read_full(self) -> np.ndarray:
        return self.read((0,) * len(self.shape), self.shape)


class ChunkStore:
    """A root N5/ZARR container on a local filesystem path."""

    def __init__(self, root: str | os.PathLike, fmt: StorageFormat):
        self.root = str(root)
        self.format = StorageFormat(fmt)
        if self.format == StorageFormat.HDF5:
            raise ValueError("use Hdf5Store for HDF5")

    # -- creation ----------------------------------------------------------

    @staticmethod
    def create(root: str | os.PathLike, fmt: StorageFormat) -> "ChunkStore":
        fmt = StorageFormat(fmt)
        store = ChunkStore(root, fmt)
        os.makedirs(store.root, exist_ok=True)
        if fmt == StorageFormat.N5:
            store._merge_json(store._attr_file(""), {"n5": "2.5.1"})
        else:
            store._merge_json(os.path.join(store.root, ".zgroup"), {"zarr_format": 2})
        return store

    @staticmethod
    def open(root: str | os.PathLike) -> "ChunkStore":
        root = str(root)
        if os.path.exists(os.path.join(root, "attributes.json")):
            return ChunkStore(root, StorageFormat.N5)
        if os.path.exists(os.path.join(root, ".zgroup")) or os.path.exists(
            os.path.join(root, ".zattrs")
        ):
            return ChunkStore(root, StorageFormat.ZARR)
        # guess by extension
        if root.rstrip("/").endswith((".zarr", ".ome.zarr")):
            return ChunkStore(root, StorageFormat.ZARR)
        return ChunkStore(root, StorageFormat.N5)

    # -- attributes --------------------------------------------------------

    def _attr_file(self, group: str) -> str:
        name = "attributes.json" if self.format == StorageFormat.N5 else ".zattrs"
        return os.path.join(self.root, group.strip("/"), name)

    @staticmethod
    def _merge_json(path: str, updates: dict) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        current: dict = {}
        if os.path.exists(path):
            with open(path) as f:
                current = json.load(f)
        current.update(updates)
        with open(path, "w") as f:
            json.dump(current, f, indent=0, default=_json_default)

    def get_attributes(self, group: str = "") -> dict:
        path = self._attr_file(group)
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return json.load(f)

    def set_attribute(self, group: str, key_path: str, value: Any) -> None:
        """N5-style nested attribute: key path split on '/'."""
        attrs = self.get_attributes(group)
        keys = [k for k in key_path.split("/") if k]
        node = attrs
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = value
        path = self._attr_file(group)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(attrs, f, indent=0, default=_json_default)

    def get_attribute(self, group: str, key_path: str, default: Any = None) -> Any:
        node: Any = self.get_attributes(group)
        for k in [k for k in key_path.split("/") if k]:
            if not isinstance(node, dict) or k not in node:
                return default
            node = node[k]
        return node

    # -- datasets ----------------------------------------------------------

    def _kvpath(self, path: str) -> str:
        return os.path.join(self.root, path.strip("/"))

    def create_dataset(
        self,
        path: str,
        shape: Sequence[int],
        block_size: Sequence[int],
        dtype: str | np.dtype,
        compression: str = "zstd",
        delete_existing: bool = False,
    ) -> Dataset:
        """Create a chunked dataset. ``shape``/``block_size`` xyz-first."""
        dtype = np.dtype(dtype).name
        if dtype not in _N5_DTYPES:
            raise ValueError(f"unsupported dtype {dtype}")
        shape = tuple(int(v) for v in shape)
        block = tuple(min(int(b), int(s)) if int(s) > 0 else int(b)
                      for b, s in zip(block_size, shape))
        if self.format == StorageFormat.N5:
            spec = {
                "driver": "n5",
                "kvstore": {"driver": "file", "path": self._kvpath(path)},
                "metadata": {
                    "dimensions": list(shape),
                    "blockSize": list(block),
                    "dataType": dtype,
                    "compression": _n5_compression(compression),
                },
                "create": True,
                "delete_existing": delete_existing,
            }
            arr = ts.open(spec).result()
            return Dataset(self, path, arr, reversed_axes=False)
        else:
            meta: dict[str, Any] = {
                "shape": list(shape[::-1]),
                "chunks": list(block[::-1]),
                "dtype": _ZARR_DTYPE[dtype],
                "compressor": _zarr_compressor(compression),
            }
            spec = {
                "driver": "zarr",
                "kvstore": {"driver": "file", "path": self._kvpath(path)},
                "metadata": meta,
                "create": True,
                "delete_existing": delete_existing,
            }
            arr = ts.open(spec).result()
            return Dataset(self, path, arr, reversed_axes=True)

    def open_dataset(self, path: str) -> Dataset:
        if self.format == StorageFormat.N5:
            spec = {
                "driver": "n5",
                "kvstore": {"driver": "file", "path": self._kvpath(path)},
                "open": True,
            }
            return Dataset(self, path, ts.open(spec).result(), reversed_axes=False)
        spec = {
            "driver": "zarr",
            "kvstore": {"driver": "file", "path": self._kvpath(path)},
            "open": True,
        }
        return Dataset(self, path, ts.open(spec).result(), reversed_axes=True)

    def is_dataset(self, path: str) -> bool:
        p = self._kvpath(path)
        if self.format == StorageFormat.N5:
            f = os.path.join(p, "attributes.json")
            if not os.path.exists(f):
                return False
            with open(f) as fh:
                return "dimensions" in json.load(fh)
        return os.path.exists(os.path.join(p, ".zarray"))

    def exists(self, path: str) -> bool:
        return os.path.exists(self._kvpath(path))

    def remove(self, path: str = "") -> None:
        p = self._kvpath(path) if path else self.root
        if os.path.exists(p):
            shutil.rmtree(p)

    def list_children(self, path: str = "") -> list[str]:
        p = self._kvpath(path)
        if not os.path.isdir(p):
            return []
        return sorted(
            d for d in os.listdir(p) if os.path.isdir(os.path.join(p, d))
        )

    def make_group(self, path: str) -> None:
        p = self._kvpath(path)
        os.makedirs(p, exist_ok=True)
        if self.format == StorageFormat.ZARR:
            zg = os.path.join(p, ".zgroup")
            if not os.path.exists(zg):
                with open(zg, "w") as f:
                    json.dump({"zarr_format": 2}, f)


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


class Hdf5Store:
    """Minimal HDF5 store (local-only, single process — the reference keeps the
    same restriction via a process-wide shared writer, N5Util.java:45-64)."""

    def __init__(self, path: str | os.PathLike, mode: str = "a"):
        import h5py

        self.path = str(path)
        self.format = StorageFormat.HDF5
        self._f = h5py.File(self.path, mode)

    def create_dataset(
        self,
        path: str,
        shape: Sequence[int],
        block_size: Sequence[int],
        dtype: str | np.dtype,
        compression: str = "gzip",
        delete_existing: bool = False,
    ) -> Dataset:
        shape = tuple(int(v) for v in shape)
        block = tuple(min(int(b), int(s)) for b, s in zip(block_size, shape))
        if delete_existing and path in self._f:
            del self._f[path]
        kw = {}
        if compression not in ("raw", "gzip"):
            raise ValueError(
                f"HDF5 store supports only gzip/raw compression, got {compression!r}"
            )
        if compression != "raw":
            kw["compression"] = "gzip"
        d = self._f.create_dataset(
            path, shape=shape[::-1], chunks=block[::-1], dtype=np.dtype(dtype), **kw
        )
        return Dataset(self, path, d, reversed_axes=True)

    def open_dataset(self, path: str) -> Dataset:
        return Dataset(self, path, self._f[path], reversed_axes=True)

    def set_attribute(self, group: str, key_path: str, value: Any) -> None:
        g = self._f.require_group(group or "/")
        g.attrs[key_path] = json.dumps(value) if isinstance(value, (dict, list)) else value

    def get_attribute(self, group: str, key_path: str, default: Any = None) -> Any:
        g = self._f.get(group or "/")
        if g is None or key_path not in g.attrs:
            return default
        v = g.attrs[key_path]
        if isinstance(v, (bytes, str)):
            try:
                return json.loads(v)
            except (json.JSONDecodeError, TypeError):
                return v
        return v

    def close(self):
        self._f.close()

"""ctypes binding for the native N5 block codec (native/blockio.cpp).

Optional fast path: ctypes foreign calls release the GIL, so a Python thread
pool over ``write_block``/``read_block`` encodes (zstd) and writes chunks
truly in parallel — the role the reference fills with prebuilt codec JNI libs
(N5Util.java:82-105, SURVEY.md §2.3). Falls back cleanly when the shared
library has not been built (``make -C native``); callers must check
``available()``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_TRIED = False

_SO_PATH = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                        "native", "libblockio.so")
_SRC_DIR = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                        "native")

COMPRESSION = {"raw": 0, "zstd": 1, "lz4": 2}


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    so = os.path.abspath(_SO_PATH)
    if not os.path.exists(so):
        try:  # build on first use; the toolchain is baked into the image
            subprocess.run(["make", "-C", os.path.abspath(_SRC_DIR)],
                           check=True, capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.n5_encode_bound.restype = ctypes.c_int64
    lib.n5_encode_bound.argtypes = [ctypes.c_int64, ctypes.c_int32]
    lib.n5_write_block_file.restype = ctypes.c_int64
    lib.n5_write_block_file.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32,
    ]
    lib.n5_read_block_file.restype = ctypes.c_int64
    lib.n5_read_block_file.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    if hasattr(lib, "n5_read_block_region"):
        lib.n5_read_block_region.restype = ctypes.c_int64
        lib.n5_read_block_region.argtypes = [
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
        ]
    if hasattr(lib, "lz4_available"):
        lib.lz4_available.restype = ctypes.c_int32
        lib.lz4_available.argtypes = []
    if hasattr(lib, "zarr_write_chunk_file"):
        lib.zarr_write_chunk_file.restype = ctypes.c_int64
        lib.zarr_write_chunk_file.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_int32,
        ]
    _LIB = lib
    return _LIB


def has_zarr() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "zarr_write_chunk_file")


def has_region_read() -> bool:
    """True when the library exports the fused strided region reader
    (n5_read_block_region) — older builds lack it and must use the
    tensorstore path."""
    lib = _load()
    return lib is not None and hasattr(lib, "n5_read_block_region")


def has_lz4() -> bool:
    """True when the native codec can serve N5 lz4 (LZ4Block) chunks —
    the library was built with the lz4 path AND liblz4 loads at runtime.
    Reference codec surface parity: util/N5Util.java:87-88."""
    lib = _load()
    return (lib is not None and hasattr(lib, "lz4_available")
            and bool(lib.lz4_available()))


def write_zarr_chunk(
    chunk_path: str,
    data: np.ndarray,
    chunk_shape: tuple[int, ...],
    compression: str = "zstd",
    level: int = 3,
    fill_value=0,
) -> None:
    """Write one zarr v2 chunk file from a strided DISK-ORDER view.

    ``data``'s axes must already be in on-disk (C) order — callers pass a
    transposed numpy VIEW (no copy; the C side walks the strides). Chunks
    shorter than ``chunk_shape`` (array edge) are padded with
    ``fill_value``."""
    lib = _load()
    if lib is None or not hasattr(lib, "zarr_write_chunk_file"):
        raise RuntimeError("native zarr chunk writer not available")
    ndim = data.ndim
    strides = (ctypes.c_int64 * ndim)(*data.strides)
    src_dims = (ctypes.c_uint32 * ndim)(*data.shape)
    chk_dims = (ctypes.c_uint32 * ndim)(*chunk_shape)
    fill = np.asarray(fill_value or 0, dtype=data.dtype).tobytes()
    got = lib.zarr_write_chunk_file(
        chunk_path.encode(), data.ctypes.data_as(ctypes.c_void_p),
        data.dtype.itemsize, strides, src_dims, chk_dims, ndim,
        ctypes.c_char_p(fill), COMPRESSION[compression], level,
    )
    if got < 0:
        raise IOError(f"zarr_write_chunk_file({chunk_path}) failed: {got}")


def available() -> bool:
    return _load() is not None


def write_block(
    block_path: str,
    data: np.ndarray,
    compression: str = "zstd",
    level: int = 3,
) -> None:
    """Encode ``data`` (xyz-first logical order) as an N5 block file.

    ``data`` axes follow the store convention (first axis fastest on disk),
    so the buffer handed to C must be Fortran-contiguous w.r.t. that order.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native blockio not available")
    arr = np.asfortranarray(data)
    dims = (ctypes.c_uint32 * arr.ndim)(*arr.shape)
    got = lib.n5_write_block_file(
        block_path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
        arr.dtype.itemsize, dims, arr.ndim, arr.size,
        COMPRESSION[compression], level,
    )
    if got < 0:
        raise IOError(f"n5_write_block_file({block_path}) failed: {got}")


def read_block_region(
    block_path: str,
    dst: np.ndarray,
    dst_offset: tuple[int, ...],
    src_lo: tuple[int, ...],
    copy_dims: tuple[int, ...],
    compression: str = "zstd",
) -> int | None:
    """Decode one N5 block file and copy ``copy_dims`` voxels starting at
    ``src_lo`` (in-chunk coords) directly into ``dst[dst_offset...]`` —
    the big-endian swap fuses with the strided write (one pass; no
    intermediate chunk array, no numpy assembly copy). Returns elements
    copied, or None if the file is absent."""
    lib = _load()
    if lib is None or not hasattr(lib, "n5_read_block_region"):
        raise RuntimeError("native blockio region read not available")
    ndim = dst.ndim
    es = dst.dtype.itemsize
    base = dst.ctypes.data + sum(
        int(dst_offset[d]) * dst.strides[d] for d in range(ndim))
    dstr = (ctypes.c_int64 * ndim)(*dst.strides)
    lo = (ctypes.c_uint32 * ndim)(*[int(v) for v in src_lo])
    cd = (ctypes.c_uint32 * ndim)(*[int(v) for v in copy_dims])
    dims = (ctypes.c_uint32 * 16)()
    nd = ctypes.c_int32()
    got = lib.n5_read_block_region(
        block_path.encode(), es, COMPRESSION[compression], ndim, lo, cd,
        ctypes.c_void_p(base), dstr, dims, ctypes.byref(nd))
    if got == -7:
        return None
    if got < 0:
        raise IOError(f"n5_read_block_region({block_path}) failed: {got}")
    return int(got)


def read_block(
    block_path: str,
    dtype: np.dtype,
    max_shape: tuple[int, ...],
    compression: str = "zstd",
) -> np.ndarray | None:
    """Decode one N5 block file -> xyz-first array, or None if absent."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native blockio not available")
    dtype = np.dtype(dtype)
    cap = int(np.prod(max_shape)) * dtype.itemsize
    out = np.empty(int(np.prod(max_shape)), dtype=dtype)
    dims = (ctypes.c_uint32 * 16)()
    ndim = ctypes.c_int32()
    got = lib.n5_read_block_file(
        block_path.encode(), dtype.itemsize, COMPRESSION[compression],
        out.ctypes.data_as(ctypes.c_void_p), cap, dims, ctypes.byref(ndim),
    )
    if got == -7:
        return None
    if got < 0:
        raise IOError(f"n5_read_block_file({block_path}) failed: {got}")
    shape = tuple(int(dims[d]) for d in range(ndim.value))
    return out[: int(np.prod(shape))].reshape(shape, order="F")

"""Async chunk prefetcher: overlap remote reads with device compute.

Against a remote object store every cold chunk read is a network round
trip, and the drivers take it synchronously on the consumer's critical
path (inside ``build()``, inside a pair crop, inside a gated streamed
read). The drivers all KNOW their future reads, though — the mesh driver
has batch k+2's source boxes while batch k runs, the pair scheduler has
the whole dispatch window's crops, the dag executor knows which published
blocks a streamed consumer is still owed — so this module turns that
knowledge into read-ahead: feeds submit future boxes, a small pool of
worker threads decodes them into the shared chunk LRU
(``Dataset.prefetch_box``), and the consumer's later read becomes a cache
hit.

Budgeting (``BST_PREFETCH_BYTES``): the prefetcher tracks every byte it
inserted that has not yet been consumed. Workers pause issuing while the
tracked backlog sits at the budget, and when new insertions push past it
the OLDEST tracked entries are untracked and counted as
``bst_io_prefetch_miss_total`` — prefetched too far ahead of the
consumer, i.e. wasted read-ahead (the entries themselves stay in the LRU
and may still hit later; only the prefetcher stops crediting itself).
Consumption is observed through a hook in ``ChunkCache.get``: a cache hit
on a tracked key counts ``bst_io_prefetch_hit_total``/``_hit_bytes_total``
and frees budget. ``BST_PREFETCH_BYTES=0`` (or 0 threads) disables
everything: submits no-op, no thread ever starts, no hook state changes —
the exact pre-prefetch code paths.

Workers are plain daemon threads, NOT ``utils.threads`` context-capturing
ones: the pool is process-lived and must not pin one job's cancel scope
or config overrides into every later fetch. A fetch that raises is
dropped silently — prefetch is advisory and must never fail a pipeline.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque

from . import chunkcache
from .. import config
from ..observe import metrics as _metrics

_HITS = _metrics.counter("bst_io_prefetch_hit_total")
_MISSES = _metrics.counter("bst_io_prefetch_miss_total")
_HIT_BYTES = _metrics.counter("bst_io_prefetch_hit_bytes_total")
# incremented by Dataset.prefetch_box (io/chunkstore.py) as it decodes;
# same registry series, referenced here for the stats() surface
_BYTES = _metrics.counter("bst_io_prefetch_bytes_total")


def budget_bytes() -> int:
    return config.get_bytes("BST_PREFETCH_BYTES")


def threads() -> int:
    return config.get_int("BST_PREFETCH_THREADS")


def enabled() -> bool:
    return budget_bytes() > 0 and threads() > 0


class Prefetcher:
    """Byte-budgeted read-ahead pool over ``Dataset.prefetch_box``.

    The queue holds thunks — zero-arg callables returning an iterable of
    ``(dataset, offset, shape)`` boxes — so feeds enqueue cheaply on the
    hot path and box enumeration runs on a worker thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._tracked: OrderedDict[tuple, int] = OrderedDict()
        self._tracked_bytes = 0
        self._inflight = 0
        self._workers: list[threading.Thread] = []
        self._stopping = False

    # -- feed API -----------------------------------------------------------

    def submit(self, thunk) -> None:
        """Enqueue a thunk of future boxes. No-op while disabled."""
        if not enabled():
            return
        self._ensure_workers()
        with self._cv:
            self._queue.append(thunk)
            self._cv.notify()

    def submit_boxes(self, boxes) -> None:
        """Enqueue concrete ``(dataset, offset, shape)`` triples — one
        queue entry each, so the pool spreads them across workers instead
        of fetching the whole list serially on one thread."""
        for box in boxes:
            self.submit(lambda b=box: (b,))

    # -- consumption (ChunkCache.get hook) ----------------------------------

    def on_cache_hit(self, key: tuple, nbytes: int) -> None:
        with self._cv:
            if key not in self._tracked:
                return
            self._untrack_locked(key)
            self._cv.notify_all()
        _HITS.inc()
        _HIT_BYTES.inc(int(nbytes))

    # -- worker side --------------------------------------------------------

    def _ensure_workers(self) -> None:
        with self._lock:
            if self._workers or self._stopping:
                return
            n = max(1, threads())
            for i in range(n):
                # raw daemon threads on purpose (see class docstring):
                # the pool is process-lived and must not pin one job's
                # cancel scope or config overrides
                t = threading.Thread(target=self._worker, daemon=True,  # bst-lint: off=thread-spawn
                                     name=f"bst-prefetch-{i}")
                self._workers.append(t)
        chunkcache.set_prefetch_hook(self.on_cache_hit)
        for t in self._workers:
            t.start()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopping:
                    self._cv.wait(0.2)
                if self._stopping:
                    return
                thunk = self._queue.popleft()
                self._inflight += 1
            try:
                for box in thunk():
                    self._fetch_one(box)
            except Exception:
                pass  # advisory: a bad feed must never take a worker down
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _fetch_one(self, box) -> None:
        ds, offset, shape = box
        budget = budget_bytes()
        if budget <= 0:
            return
        # pace on the unconsumed backlog: wait (bounded — a consumer that
        # never shows up must not wedge the pool) for hits to free budget
        deadline = 10  # x 0.1s
        with self._cv:
            while self._tracked_bytes >= budget and deadline > 0:
                self._cv.wait(0.1)
                deadline -= 1
        try:
            inserted = ds.prefetch_box(offset, shape)
        except Exception:
            return
        if not inserted:
            return
        with self._cv:
            for key, nb in inserted:
                self._untrack_locked(key)  # re-prefetch refreshes position
                self._tracked[key] = int(nb)
                self._tracked_bytes += int(nb)
            while self._tracked_bytes > budget and self._tracked:
                # past the read-ahead window: oldest entries were fetched
                # too early — untrack and count them as wasted prefetch
                k, _nb = self._tracked.popitem(last=False)
                self._tracked_bytes -= _nb
                _MISSES.inc()

    def _untrack_locked(self, key: tuple) -> None:
        nb = self._tracked.pop(key, None)
        if nb is not None:
            self._tracked_bytes -= nb

    # -- lifecycle / introspection ------------------------------------------

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until the queue is empty and no fetch is in flight (tests
        and bench legs use this to make prefetch effects deterministic)."""
        deadline = timeout_s
        with self._cv:
            while self._queue or self._inflight:
                if deadline <= 0:
                    return False
                self._cv.wait(0.1)
                deadline -= 0.1
        return True

    def reset(self) -> None:
        """Drop queued work and all tracking state (between bench legs /
        tests). Workers stay up; counters are NOT reset."""
        with self._cv:
            self._queue.clear()
            self._tracked.clear()
            self._tracked_bytes = 0
            self._cv.notify_all()

    def stats_snapshot(self) -> dict:
        with self._lock:
            return {"tracked_bytes": self._tracked_bytes,
                    "tracked_entries": len(self._tracked),
                    "queued": len(self._queue),
                    "workers": len(self._workers)}


_PF = Prefetcher()


def get_prefetcher() -> Prefetcher:
    return _PF


def submit(thunk) -> None:
    _PF.submit(thunk)


def submit_boxes(boxes) -> None:
    _PF.submit_boxes(boxes)


def drain(timeout_s: float = 30.0) -> bool:
    return _PF.drain(timeout_s)


def reset() -> None:
    _PF.reset()


def stats() -> dict:
    """Lifetime prefetch effectiveness + live backlog — folded into
    ``ChunkCache.stats()`` so every warmth surface (`bst jobs`, `bst top`,
    relay snapshots, `/status`) reports it."""
    return {**_PF.stats_snapshot(),
            "hits": _HITS.value, "misses": _MISSES.value,
            "hit_bytes": _HIT_BYTES.value, "bytes": _BYTES.value}

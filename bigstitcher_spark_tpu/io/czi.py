"""Minimal Zeiss CZI (ZISRAW) reader: enough to ingest tiled/multi-view
light-sheet acquisitions as a resave input.

The reference resaves CZI-backed BigStitcher projects through bioformats
imgloaders (``spimreconstruction.filemap2`` XML: per-view (file, series,
channel) mappings consumed by FileMapImgLoaderLOCI; resave entry
SparkResaveN5.java:107-457). This is a from-scratch parser of the public
ZISRAW container layout — segment stream + subblock directory — supporting
uncompressed subblocks (compression 0), the common case for raw microscope
output. Pyramid subblocks (PyramidType != 0) are ignored; series maps to the
CZI scene (S) dimension the way bioformats enumerates scenes.

No code or structure is taken from any Zeiss SDK; the layout constants follow
the openly documented file format.
"""

from __future__ import annotations

import os
import struct
import threading
from dataclasses import dataclass, field

import numpy as np

SEGMENT_HEADER = struct.Struct("<16sqq")  # id, allocated, used
FILE_HEADER = struct.Struct("<ii8x16s16siqqiq")
# major, minor, (reserved), primary guid, file guid, part,
# directory_pos, metadata_pos, update_pending, attachment_dir_pos
DIR_ENTRY_FIXED = struct.Struct("<2siqiiB5xi")
# schema "DV", pixel_type, file_position, file_part, compression,
# pyramid_type, (reserved), dimension_count
DIM_ENTRY = struct.Struct("<4siifi")
# dimension, start, size, start_coordinate, stored_size
SUBBLOCK_FIXED = struct.Struct("<iiq")  # metadata_size, attachment_size, data_size

PIXEL_DTYPES = {
    0: np.dtype("uint8"),     # Gray8
    1: np.dtype("uint16"),    # Gray16
    12: np.dtype("float32"),  # Gray32Float
}


@dataclass
class SubBlockEntry:
    file_position: int
    pixel_type: int
    compression: int
    pyramid_type: int
    dims: dict[str, tuple[int, int]] = field(default_factory=dict)
    # dimension -> (start, size); stored_size tracked for X/Y
    stored: dict[str, int] = field(default_factory=dict)

    def start(self, d: str, default: int = 0) -> int:
        return self.dims.get(d, (default, 1))[0]

    def size(self, d: str, default: int = 1) -> int:
        return self.dims.get(d, (0, default))[1]


class CziFile:
    """Random-access reader over one .czi file (thread-safe reads)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "rb")
        sid, _alloc, _used, data_off = self._read_segment_header(0)
        if sid != b"ZISRAWFILE":
            raise ValueError(f"{path}: not a CZI file (got {sid!r})")
        raw = self._pread(data_off, FILE_HEADER.size)
        (_major, _minor, _pguid, _fguid, _part, dir_pos, meta_pos,
         _pending, _attach) = FILE_HEADER.unpack(raw)
        self.metadata_position = meta_pos
        self.entries = self._read_directory(dir_pos) if dir_pos > 0 else []

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- low-level ---------------------------------------------------------

    def _pread(self, offset: int, size: int) -> bytes:
        with self._lock:
            self._fh.seek(offset)
            return self._fh.read(size)

    def _read_segment_header(self, offset: int):
        raw = self._pread(offset, SEGMENT_HEADER.size)
        if len(raw) < SEGMENT_HEADER.size:
            raise EOFError(f"{self.path}: truncated segment at {offset}")
        sid, alloc, used = SEGMENT_HEADER.unpack(raw)
        return sid.rstrip(b"\x00 "), alloc, used, offset + SEGMENT_HEADER.size

    def _read_directory(self, dir_pos: int) -> list[SubBlockEntry]:
        sid, _alloc, used, data_off = self._read_segment_header(dir_pos)
        if sid != b"ZISRAWDIRECTORY":
            raise ValueError(f"{self.path}: bad directory segment {sid!r}")
        raw = self._pread(data_off, used)
        (count,) = struct.unpack_from("<i", raw, 0)
        pos = 128  # 4-byte count + 124 reserved
        entries = []
        for _ in range(count):
            e, pos = self._parse_dir_entry(raw, pos)
            entries.append(e)
        return entries

    @staticmethod
    def _parse_dir_entry(raw: bytes, pos: int) -> tuple[SubBlockEntry, int]:
        (schema, pixel_type, file_position, _file_part, compression,
         pyramid_type, dim_count) = DIR_ENTRY_FIXED.unpack_from(raw, pos)
        if schema != b"DV":
            raise ValueError(f"unsupported directory entry schema {schema!r}")
        pos += DIR_ENTRY_FIXED.size
        e = SubBlockEntry(file_position, pixel_type, compression, pyramid_type)
        for _ in range(dim_count):
            dim, start, size, _startc, stored = DIM_ENTRY.unpack_from(raw, pos)
            pos += DIM_ENTRY.size
            name = dim.rstrip(b"\x00 ").decode("ascii")
            e.dims[name] = (start, size)
            e.stored[name] = stored
        return e, pos

    @staticmethod
    def _entry_size(e: SubBlockEntry) -> int:
        return DIR_ENTRY_FIXED.size + DIM_ENTRY.size * len(e.dims)

    def read_subblock(self, e: SubBlockEntry) -> np.ndarray:
        """Decode one subblock as a (Y, X) or (Z, Y, X) array."""
        sid, _alloc, used, data_off = self._read_segment_header(e.file_position)
        if sid != b"ZISRAWSUBBLOCK":
            raise ValueError(f"{self.path}: bad subblock segment {sid!r}")
        raw = self._pread(data_off, SUBBLOCK_FIXED.size)
        metadata_size, _attach_size, data_size = SUBBLOCK_FIXED.unpack(raw)
        # the header size depends on the DirectoryEntry EMBEDDED in the
        # subblock segment; parse it rather than assuming the file
        # directory's copy has the same dimension count (ADVICE r4 — a
        # writer may store extra per-subblock dimensions), falling back to
        # the directory copy if the embedded bytes don't parse
        try:
            emb = self._pread(data_off + SUBBLOCK_FIXED.size,
                              DIR_ENTRY_FIXED.size)
            (emb_schema, _pt, _fp, _part, _comp, _pyr,
             emb_dim_count) = DIR_ENTRY_FIXED.unpack(emb)
            if emb_schema != b"DV" or not (0 <= emb_dim_count <= 64):
                raise ValueError("embedded entry not DV")
            emb_size = DIR_ENTRY_FIXED.size + DIM_ENTRY.size * emb_dim_count
        except (ValueError, EOFError, struct.error):
            emb_size = self._entry_size(e)
        header_size = max(256, SUBBLOCK_FIXED.size + emb_size)
        payload_off = data_off + header_size + metadata_size
        dtype = PIXEL_DTYPES.get(e.pixel_type)
        if dtype is None:
            raise NotImplementedError(
                f"{self.path}: CZI pixel type {e.pixel_type} not supported "
                f"(supported: Gray8/Gray16/Gray32Float)")
        if e.compression != 0:
            raise NotImplementedError(
                f"{self.path}: compressed CZI subblocks (compression="
                f"{e.compression}) not supported; resave from uncompressed "
                "CZI or convert with Zeiss tools first")
        sx = e.stored.get("X", e.size("X"))
        sy = e.stored.get("Y", e.size("Y"))
        sz = e.size("Z", 1) if "Z" in e.dims else 1
        count = sx * sy * sz
        buf = self._pread(payload_off, count * dtype.itemsize)
        if len(buf) < count * dtype.itemsize or data_size < count * dtype.itemsize:
            raise EOFError(f"{self.path}: truncated subblock payload")
        arr = np.frombuffer(buf, dtype=dtype, count=count)
        return arr.reshape((sz, sy, sx)) if sz > 1 else arr.reshape((sy, sx))

    # -- volume assembly ---------------------------------------------------

    def scenes(self) -> list[int]:
        ids = {e.start("S", 0) for e in self.entries if e.pyramid_type == 0}
        return sorted(ids)

    def read_volume(self, scene: int = 0, channel: int = 0,
                    timepoint: int = 0, illumination: int | None = None
                    ) -> np.ndarray:
        """Assemble the (X, Y, Z) volume of one view.

        Subblocks are placed by their Z start; X/Y starts are normalized to
        the scene's minimum (mosaic-free single-tile scenes — the BigStitcher
        Z.1/tiled-acquisition case where each scene is one stack)."""
        sel = [
            e for e in self.entries
            if e.pyramid_type == 0
            and e.start("S", 0) == scene
            and e.start("C", 0) == channel
            and e.start("T", 0) == timepoint
            and (illumination is None or e.start("I", 0) == illumination)
        ]
        if not sel:
            raise ValueError(
                f"{self.path}: no subblocks for scene={scene} "
                f"channel={channel} timepoint={timepoint}")
        # refuse silent overlay: any dimension beyond the filtered/spatial
        # ones that still varies (e.g. I illumination, R rotation) would make
        # subblocks overwrite each other last-write-wins
        filtered = {"X", "Y", "Z", "S", "C", "T"}
        if illumination is not None:
            filtered.add("I")
        varying = {
            d for e in sel for d in e.dims
            if d not in filtered
            and len({x.start(d, 0) for x in sel}) > 1
        }
        if varying:
            raise NotImplementedError(
                f"{self.path}: subblocks vary in unhandled CZI dimension(s) "
                f"{sorted(varying)} for scene={scene} channel={channel}; "
                "pass illumination= for I, other dimensions are not "
                "supported by the filemap loader")
        x0 = min(e.start("X", 0) for e in sel)
        y0 = min(e.start("Y", 0) for e in sel)
        z0 = min(e.start("Z", 0) for e in sel)
        nx = max(e.start("X", 0) - x0 + e.size("X") for e in sel)
        ny = max(e.start("Y", 0) - y0 + e.size("Y") for e in sel)
        nz = max(e.start("Z", 0) - z0 + e.size("Z", 1) for e in sel)
        dtype = PIXEL_DTYPES.get(sel[0].pixel_type)
        if dtype is None:
            raise NotImplementedError(
                f"{self.path}: CZI pixel type {sel[0].pixel_type} not supported")
        vol = np.zeros((nz, ny, nx), dtype=dtype)
        for e in sel:
            plane = self.read_subblock(e)
            zs = e.start("Z", 0) - z0
            ys = e.start("Y", 0) - y0
            xs = e.start("X", 0) - x0
            if plane.ndim == 2:
                vol[zs, ys:ys + plane.shape[0], xs:xs + plane.shape[1]] = plane
            else:
                vol[zs:zs + plane.shape[0], ys:ys + plane.shape[1],
                    xs:xs + plane.shape[2]] = plane
        return vol.transpose(2, 1, 0)  # (X, Y, Z)

    def metadata_xml(self) -> str:
        if self.metadata_position <= 0:
            return ""
        sid, _alloc, used, data_off = self._read_segment_header(
            self.metadata_position)
        if sid != b"ZISRAWMETADATA":
            return ""
        raw = self._pread(data_off, 16)
        (xml_size,) = struct.unpack_from("<i", raw, 0)
        return self._pread(data_off + 256, xml_size).decode(
            "utf-8", errors="replace")


# ---------------------------------------------------------------------------
# Writer — test/fixture support (also makes this module self-verifying: the
# reader is exercised against files produced to the same public layout).
# ---------------------------------------------------------------------------


def _pixel_type_of(dtype) -> int:
    for pt, dt in PIXEL_DTYPES.items():
        if dt == np.dtype(dtype):
            return pt
    raise ValueError(f"unsupported dtype for CZI: {dtype}")


def write_czi(path: str, views: list[dict]) -> None:
    """Write a minimal CZI: one uncompressed Z-plane subblock per slice.

    ``views``: dicts with keys ``data`` ((X,Y,Z) array) and optional
    ``scene``/``channel``/``timepoint``/``illumination`` ints."""
    segments = []  # (id, payload bytes) — positions patched at the end

    def seg(sid: bytes, payload: bytes) -> int:
        segments.append([sid, payload])
        return len(segments) - 1

    entries = []  # (entry_bytes_fn, segment_index)
    dir_entries_raw = []

    for v in views:
        data = np.asarray(v["data"])
        if data.ndim != 3:
            raise ValueError("view data must be (X, Y, Z)")
        pt = _pixel_type_of(data.dtype)
        zyx = data.transpose(2, 1, 0)  # (Z, Y, X) planes
        for z in range(zyx.shape[0]):
            plane = np.ascontiguousarray(zyx[z])
            dims = [
                (b"X", 0, plane.shape[1], plane.shape[1]),
                (b"Y", 0, plane.shape[0], plane.shape[0]),
                (b"Z", z, 1, 1),
                (b"C", int(v.get("channel", 0)), 1, 1),
                (b"T", int(v.get("timepoint", 0)), 1, 1),
                (b"S", int(v.get("scene", 0)), 1, 1),
            ]
            if "illumination" in v:
                dims.append((b"I", int(v["illumination"]), 1, 1))
            entry_fixed_args = (b"DV", pt, 0, 0, 0, 0, len(dims))
            dim_bytes = b"".join(
                DIM_ENTRY.pack(d, start, size, float(start), stored)
                for d, start, size, stored in dims)
            entry_size = DIR_ENTRY_FIXED.size + len(dim_bytes)
            header_size = max(256, SUBBLOCK_FIXED.size + entry_size)
            payload = plane.tobytes()
            sub = bytearray()
            sub += SUBBLOCK_FIXED.pack(0, 0, len(payload))
            sub += DIR_ENTRY_FIXED.pack(*entry_fixed_args)
            sub += dim_bytes
            sub += b"\x00" * (header_size - len(sub))
            sub += payload
            idx = seg(b"ZISRAWSUBBLOCK", bytes(sub))
            dir_entries_raw.append((entry_fixed_args, dim_bytes, idx))

    # layout: file header first, then subblocks, then directory; the header
    # payload is packed once positions are known (placeholder sizes match:
    # FILE_HEADER is fixed-size)
    out_positions = {}
    offset = 0
    blobs = []
    all_segments = [[b"ZISRAWFILE", b"\x00" * FILE_HEADER.size]] + segments
    for i, (sid, payload) in enumerate(all_segments):
        alloc = ((max(len(payload), 32) + 31) // 32) * 32  # 32-byte alignment
        out_positions[i] = offset
        blobs.append((sid, payload, alloc))
        offset += SEGMENT_HEADER.size + alloc
    dir_pos = offset

    dir_body = bytearray()
    dir_body += struct.pack("<i", len(dir_entries_raw))
    dir_body += b"\x00" * 124
    for entry_fixed_args, dim_bytes, idx in dir_entries_raw:
        args = list(entry_fixed_args)
        args[2] = out_positions[idx + 1]  # +1: file header prepended
        dir_body += DIR_ENTRY_FIXED.pack(*args)
        dir_body += dim_bytes

    with open(path, "wb") as f:
        for i, (sid, payload, alloc) in enumerate(blobs):
            if sid == b"ZISRAWFILE":
                payload = FILE_HEADER.pack(1, 0, b"\x00" * 16, b"\x00" * 16,
                                           0, dir_pos, 0, 0, 0)
            f.write(SEGMENT_HEADER.pack(sid.ljust(16, b"\x00"), alloc,
                                        len(payload)))
            f.write(payload.ljust(alloc, b"\x00"))
        f.write(SEGMENT_HEADER.pack(b"ZISRAWDIRECTORY".ljust(16, b"\x00"),
                                    len(dir_body), len(dir_body)))
        f.write(bytes(dir_body))


__all__ = ["CziFile", "write_czi", "PIXEL_DTYPES"]

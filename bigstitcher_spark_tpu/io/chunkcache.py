"""Process-wide byte-budgeted LRU cache of DECODED chunks.

The chunk decode path re-reads the same compressed chunks for every
overlapping output box: fusion halos (adjacent compute blocks expand by a
voxel), detection blocks (2*halo overlap per block edge), downsample
pyramids (every level re-reads its parent), and repeated runs over the
same inputs all decode identical chunks again. This cache sits under
``Dataset.read`` for every driver (native N5 codec, tensorstore, h5py) so
each chunk decodes ONCE per process while the budget holds.

Keys are ``(dataset_key, meta_sig, chunk_pos)``:

- ``dataset_key`` = (store root, dataset path) — content-addressed, so
  independent ``Dataset``/``ChunkStore`` instances over the same on-disk
  array SHARE entries (cross-reader sharing);
- ``meta_sig`` = the dataset metadata file's (mtime_ns, size) signature
  (the same signature ``Dataset._meta_file_cached`` keys on) — recreating
  a dataset at the same path orphans the old entries;
- ``chunk_pos`` = the chunk's grid position.

Writes invalidate: ``Dataset.write`` drops exactly the chunk positions the
written box covers (any signature), and store-level remove/recreate drops
every entry under the path prefix. Each invalidation also bumps a
per-dataset GENERATION counter that device-side caches (the composite
fusion tile cache) fold into their keys, so host-visible mutation
propagates to HBM-resident copies too.

``BST_CHUNK_CACHE_BYTES`` sets the budget (default 1 GiB); ``0`` disables
caching entirely — reads then take exactly the pre-cache code paths, so
cache-off output is bit-identical by construction.

Eligibility: local filesystems, ``memory://`` roots and single-process
HDF5 always participate. Remote object stores (s3/gs) participate under
``BST_REMOTE_CACHE=run`` (the default): their entries fold a per-run pin
plus the dataset metadata object's content hash into ``meta_sig``, so the
coherence window is explicit — this process's own writes invalidate via
the generation bumps below, while an EXTERNAL process mutating chunk
objects mid-run is outside the contract (``off`` restores the historical
remote bypass bit-identically; see README "Configuration").

Under this LRU sits an optional disk spill tier (io/disktier.py,
``BST_DISK_TIER_BYTES``): budget-pressure evictions spill to a run-scoped
local directory and ``get`` promotes them back on the next miss, so
working sets larger than RAM stop re-fetching from the store. All
invalidation paths pass through to it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from . import disktier
from .. import config
from ..observe import metrics as _metrics

_HITS = _metrics.counter("bst_chunk_cache_hits_total")
_MISSES = _metrics.counter("bst_chunk_cache_misses_total")
_HIT_BYTES = _metrics.counter("bst_chunk_cache_hit_bytes_total")
_MISS_BYTES = _metrics.counter("bst_chunk_cache_miss_bytes_total")
_EVICTIONS = _metrics.counter("bst_chunk_cache_evictions_total")
_EVICT_BYTES = _metrics.counter("bst_chunk_cache_evict_bytes_total")
_INVALIDATIONS = _metrics.counter("bst_chunk_cache_invalidations_total")
_CUR_BYTES = _metrics.gauge("bst_chunk_cache_bytes")
_CUR_ENTRIES = _metrics.gauge("bst_chunk_cache_entries")


def budget_bytes() -> int:
    """Current byte budget (read through the config registry on every call
    so tests and long-lived processes can retune without restarting)."""
    return config.get_bytes("BST_CHUNK_CACHE_BYTES")


def enabled() -> bool:
    return budget_bytes() > 0


# consumption hook of the async prefetcher (io/prefetch.py): installed once
# when a prefetcher first activates, then permanent — the hook itself
# short-circuits when nothing is tracked, so the cost off-prefetch is the
# _DAG_HOOKS pattern's one list-load + None check
_PREFETCH_HOOK: list = [None]


def set_prefetch_hook(fn) -> None:
    _PREFETCH_HOOK[0] = fn


class ChunkCache:
    """Thread-safe byte-budgeted LRU over decoded chunk arrays.

    Stored arrays are private contiguous copies marked read-only; readers
    always copy out of them into their own output buffers, so a cached
    chunk can never alias caller-visible memory."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self._by_dataset: dict[tuple, set] = {}
        self._generations: dict[tuple, int] = {}
        self._bytes = 0
        # evictions between leaving the LRU and landing on the disk tier:
        # keeping them readable here closes the window where a concurrent
        # lookup finds the chunk in NEITHER tier and re-fetches it remotely
        self._spilling: dict[tuple, np.ndarray] = {}

    # -- lookup ------------------------------------------------------------

    def get(self, key: tuple) -> np.ndarray | None:
        with self._lock:
            arr = self._entries.get(key)
            if arr is not None:
                self._entries.move_to_end(key)
            elif self._spilling:
                arr = self._spilling.get(key)
        if arr is None:
            # memory miss: promote from the disk spill tier when it holds
            # the key (has_entries() keeps the never-spilled path exactly
            # the pre-tier code)
            tier = disktier.get_tier()
            if tier.has_entries():
                arr = tier.load(key)
                if arr is not None:
                    self.put(key, arr, record_miss=False)
            if arr is None:
                _MISSES.inc()
                return None
        hook = _PREFETCH_HOOK[0]
        if hook is not None:
            hook(key, arr.nbytes)
        _HITS.inc()
        _HIT_BYTES.inc(arr.nbytes)
        return arr

    def peek(self, key: tuple) -> bool:
        """Non-counting residency probe (memory OR disk tier): the
        prefetcher plans with it, so probes never skew hit/miss stats,
        never touch LRU order and never fire the consumption hook."""
        with self._lock:
            if key in self._entries or key in self._spilling:
                return True
        tier = disktier.get_tier()
        return tier.has_entries() and tier.contains(key)

    def put(self, key: tuple, arr: np.ndarray,
            record_miss: bool = True) -> None:
        """Insert a decoded chunk. ``record_miss=False`` marks a
        write-through insertion (the streaming DAG handoff populating the
        cache from a producer's write) rather than a decode after a cache
        miss, so the miss-byte counter keeps meaning what it says."""
        budget = budget_bytes()
        if arr.nbytes > budget:
            if record_miss:
                _MISS_BYTES.inc(arr.nbytes)
            return
        arr = np.ascontiguousarray(arr)
        arr.setflags(write=False)
        evicted = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = arr
            self._by_dataset.setdefault(key[0], set()).add(key)
            self._bytes += arr.nbytes
            while self._bytes > budget and self._entries:
                k, v = self._entries.popitem(last=False)
                self._by_dataset.get(k[0], set()).discard(k)
                self._bytes -= v.nbytes
                evicted.append((k, v))
            spill_down = bool(evicted) and disktier.enabled()
            if spill_down:
                for k, v in evicted:
                    self._spilling[k] = v
            self._update_gauges()
        if record_miss:
            _MISS_BYTES.inc(arr.nbytes)
        for _k, v in evicted:
            _EVICTIONS.inc()
            _EVICT_BYTES.inc(v.nbytes)
        if spill_down:
            # budget-pressure evictions drop to the disk tier (outside the
            # lock: file IO must never serialize the hot path; the
            # _spilling map keeps them readable until the files land)
            disktier.get_tier().spill(evicted)
            with self._lock:
                for k, _v in evicted:
                    self._spilling.pop(k, None)

    # -- invalidation ------------------------------------------------------

    def invalidate(self, dataset_key: tuple,
                   chunk_positions=None) -> None:
        """Drop a dataset's entries (all of them, or only the listed chunk
        positions — any metadata signature) and bump its generation.

        Runs even when caching is disabled: the generation counter is how
        device-side caches observe writes, and it must advance regardless
        of whether host chunks were retained. Spilled entries drop with
        the memory ones — a generation bump reaches the disk tier too."""
        wanted = (None if chunk_positions is None
                  else {tuple(int(v) for v in p) for p in chunk_positions})
        with self._lock:
            self._generations[dataset_key] = (
                self._generations.get(dataset_key, 0) + 1)
            keys = self._by_dataset.get(dataset_key)
            if keys:
                doomed = (list(keys) if wanted is None
                          else [k for k in keys if k[2] in wanted])
                for k in doomed:
                    v = self._entries.pop(k, None)
                    keys.discard(k)
                    if v is not None:
                        self._bytes -= v.nbytes
                        _INVALIDATIONS.inc()
                if not keys:
                    self._by_dataset.pop(dataset_key, None)
                self._update_gauges()
            if self._spilling:
                for k in [k for k in self._spilling
                          if k[0] == dataset_key
                          and (wanted is None or k[2] in wanted)]:
                    self._spilling.pop(k, None)
        tier = disktier.get_tier()
        if tier.has_entries():
            tier.drop(dataset_key, wanted)

    def invalidate_prefix(self, root, path_prefix: str) -> None:
        """Drop every dataset under ``path_prefix`` of ``root`` (store-level
        remove / recreate; an empty prefix clears the whole root)."""
        prefix = path_prefix.strip("/")
        with self._lock:
            candidates = (set(self._by_dataset) | set(self._generations)
                          | set(disktier.get_tier().dataset_keys()))
            victims = [dk for dk in candidates
                       if dk[0] == root
                       and (not prefix
                            or dk[1].strip("/") == prefix
                            or dk[1].strip("/").startswith(prefix + "/"))]
        for dk in victims:
            self.invalidate(dk)

    def generation(self, dataset_key: tuple) -> int:
        with self._lock:
            return self._generations.get(dataset_key, 0)

    # -- maintenance / introspection ---------------------------------------

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_dataset.clear()
            self._spilling.clear()
            self._bytes = 0
            self._update_gauges()
        disktier.get_tier().clear()

    def stats(self) -> dict:
        """Residency + lifetime hit/miss totals — the `bst serve` daemon's
        cache-warmth surface (`bst jobs` prints it so a client can see WHY
        a repeat submit is cheap). Carries the disk spill tier and the
        async prefetcher as sub-dicts, so relay snapshots, `/status` and
        `bst top` report the whole tiered-IO warmth picture per process."""
        with self._lock:
            resident = {"entries": len(self._entries), "bytes": self._bytes}
        from . import prefetch as _prefetch

        return {**resident,
                "hits": _HITS.value, "misses": _MISSES.value,
                "hit_bytes": _HIT_BYTES.value,
                "evictions": _EVICTIONS.value,
                "disk": disktier.get_tier().stats(),
                "prefetch": _prefetch.stats()}

    def _update_gauges(self) -> None:
        _CUR_BYTES.set(self._bytes)
        _CUR_ENTRIES.set(len(self._entries))


_CACHE = ChunkCache()


def get_cache() -> ChunkCache:
    return _CACHE

"""NVMe/local-disk spill tier under the decoded-chunk LRU.

The memory LRU (io/chunkcache.py) is the only thing standing between a
consumer and a chunk re-decode; once the working set outgrows
``BST_CHUNK_CACHE_BYTES`` every eviction is a future re-fetch — from a
REMOTE object store, a full network round trip. This tier catches those
evictions: entries the memory LRU pushes out under budget pressure are
serialized to a byte-budgeted run-scoped local directory
(``BST_DISK_TIER_BYTES`` / ``BST_DISK_TIER_DIR``) and promoted back into
the memory LRU on the next miss, so working sets larger than RAM stop
paying the store again. It generalizes the dag executor's per-spec
``"backing": "disk"`` spill to EVERY cached dataset.

Tiering is INCLUSIVE: ``load`` promotes a copy and leaves the disk entry
in place, so a chunk bouncing between tiers is never momentarily in
neither (a concurrent prefetch probe in that gap would re-fetch it from
the remote store), and re-evicting a promoted chunk skips the rewrite —
the bytes on disk are still current, because any write that could change
them invalidates both tiers first. Keys are the chunk cache's own
``(dataset_key, meta_sig, chunk_pos)`` tuples, so write invalidation and
generation bumps drop disk entries through the same calls that drop
memory entries (the chunk cache forwards them). Files are anonymous ``<seq>.npy`` blobs named only by the
in-memory index; the directory is deleted at process exit — the tier is
run-scoped by construction, never a cross-run cache.

``BST_DISK_TIER_BYTES=0`` (the default) disables the tier: nothing is
ever written, and the chunk cache's probe short-circuits on an empty
index, so the memory-only paths are exactly the pre-tier code.
"""

from __future__ import annotations

import atexit
import os
import shutil
import tempfile
import threading
from collections import OrderedDict

import numpy as np

from .. import config, profiling
from ..observe import metrics as _metrics

_HIT_BYTES = _metrics.counter("bst_io_disktier_hit_bytes_total")
_SPILL_BYTES = _metrics.counter("bst_io_disktier_spill_bytes_total")
_EVICT_BYTES = _metrics.counter("bst_io_disktier_evict_bytes_total")
_CUR_BYTES = _metrics.gauge("bst_io_disktier_bytes")
_CUR_ENTRIES = _metrics.gauge("bst_io_disktier_entries")


def budget_bytes() -> int:
    return config.get_bytes("BST_DISK_TIER_BYTES")


def enabled() -> bool:
    return budget_bytes() > 0


class DiskTier:
    """Thread-safe byte-budgeted LRU of spilled decoded chunks on disk.

    The index (key -> (file path, nbytes)) is authoritative; file IO
    always happens OUTSIDE the lock (an entry is unreachable the moment
    it leaves the index, so a popped path can be read or unlinked without
    racing a concurrent spill, which always allocates a fresh name)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._index: OrderedDict[tuple, tuple[str, int]] = OrderedDict()
        self._by_dataset: dict[tuple, set] = {}
        self._bytes = 0
        self._seq = 0
        self._dir: str | None = None

    # -- directory lifecycle -----------------------------------------------

    def _ensure_dir(self) -> str:
        with self._lock:
            if self._dir is not None:
                return self._dir
            base = config.get_str("BST_DISK_TIER_DIR")
            if base:
                d = os.path.join(base, f"bst-disktier-{os.getpid()}")
                os.makedirs(d, exist_ok=True)
            else:
                d = tempfile.mkdtemp(prefix="bst-disktier-")
            self._dir = d
        atexit.register(shutil.rmtree, d, True)
        return d

    def _alloc_path_locked(self) -> str:
        self._seq += 1
        return os.path.join(self._dir or "", f"{self._seq:08x}.npy")

    # -- spill / promote ----------------------------------------------------

    def spill(self, items) -> None:
        """Persist ``[(key, arr), ...]`` (memory-LRU evictions). Oversized
        arrays are skipped; over-budget insertion evicts oldest entries."""
        budget = budget_bytes()
        if budget <= 0 or not items:
            return
        self._ensure_dir()
        for key, arr in items:
            nb = int(arr.nbytes)
            if nb > budget:
                continue
            with self._lock:
                if key in self._index:
                    # promoted earlier and evicted again: the disk copy is
                    # still current (writes invalidate both tiers), so just
                    # refresh recency instead of rewriting the file
                    self._index.move_to_end(key)
                    continue
                path = self._alloc_path_locked()
            try:
                with profiling.span("io.disktier", stage="spill",
                                    nbytes=nb):
                    np.save(path, arr, allow_pickle=False)
            except OSError:
                continue  # a full/unwritable spill dir must never fail IO
            doomed = []
            with self._lock:
                old = self._index.pop(key, None)
                if old is not None:
                    self._bytes -= old[1]
                    doomed.append(old)
                self._index[key] = (path, nb)
                self._by_dataset.setdefault(key[0], set()).add(key)
                self._bytes += nb
                while self._bytes > budget and self._index:
                    k, ent = self._index.popitem(last=False)
                    self._by_dataset.get(k[0], set()).discard(k)
                    self._bytes -= ent[1]
                    doomed.append(ent)
                    _EVICT_BYTES.inc(ent[1])
                self._update_gauges_locked()
            _SPILL_BYTES.inc(nb)
            for p, _nb in doomed:
                _unlink(p)

    def load(self, key: tuple) -> np.ndarray | None:
        """Return a spilled chunk (the caller promotes it back into the
        memory LRU), or None on miss. The disk entry STAYS resident —
        removing it here would open a window where the chunk is in
        neither tier and a concurrent prefetch probe re-fetches it from
        the remote store; a later re-eviction finds it and skips the
        rewrite instead."""
        with self._lock:
            ent = self._index.get(key)
            if ent is not None:
                self._index.move_to_end(key)
        if ent is None:
            return None
        path, nb = ent
        try:
            with profiling.span("io.disktier", stage="load", nbytes=nb):
                arr = np.load(path, allow_pickle=False)
        except (OSError, ValueError):
            # unreadable blob: drop the index entry so the miss is decisive
            with self._lock:
                if self._index.get(key) is ent:
                    self._index.pop(key, None)
                    self._by_dataset.get(key[0], set()).discard(key)
                    self._bytes -= ent[1]
                    self._update_gauges_locked()
            _unlink(path)
            return None
        _HIT_BYTES.inc(nb)
        return arr

    def contains(self, key: tuple) -> bool:
        with self._lock:
            return key in self._index

    def has_entries(self) -> bool:
        """Cheap unlocked probe: False keeps the chunk cache's miss path
        byte-identical to the pre-tier code when nothing ever spilled."""
        return bool(self._index)

    # -- invalidation -------------------------------------------------------

    def drop(self, dataset_key: tuple, wanted: set | None = None) -> None:
        """Drop a dataset's spilled entries (all, or only the chunk
        positions in ``wanted``) — the chunk cache forwards every write
        invalidation here so a generation bump reaches the disk tier."""
        with self._lock:
            keys = self._by_dataset.get(dataset_key)
            if not keys:
                return
            doomed_keys = (list(keys) if wanted is None
                           else [k for k in keys if k[2] in wanted])
            doomed = self._drop_keys_locked(dataset_key, doomed_keys)
        for p, nb in doomed:
            _EVICT_BYTES.inc(nb)
            _unlink(p)

    def drop_prefix(self, root, path_prefix: str) -> None:
        prefix = path_prefix.strip("/")
        with self._lock:
            victims = [dk for dk in list(self._by_dataset)
                       if dk[0] == root
                       and (not prefix
                            or dk[1].strip("/") == prefix
                            or dk[1].strip("/").startswith(prefix + "/"))]
            doomed = []
            for dk in victims:
                doomed += self._drop_keys_locked(
                    dk, list(self._by_dataset.get(dk, ())))
        for p, nb in doomed:
            _EVICT_BYTES.inc(nb)
            _unlink(p)

    def _drop_keys_locked(self, dataset_key, keys) -> list:
        out = []
        live = self._by_dataset.get(dataset_key, set())
        for k in keys:
            ent = self._index.pop(k, None)
            live.discard(k)
            if ent is not None:
                self._bytes -= ent[1]
                out.append(ent)
        if not live:
            self._by_dataset.pop(dataset_key, None)
        self._update_gauges_locked()
        return out

    def dataset_keys(self) -> list[tuple]:
        with self._lock:
            return list(self._by_dataset)

    def clear(self) -> None:
        with self._lock:
            doomed = list(self._index.values())
            self._index.clear()
            self._by_dataset.clear()
            self._bytes = 0
            self._update_gauges_locked()
        for p, _nb in doomed:
            _unlink(p)

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            resident = {"entries": len(self._index), "bytes": self._bytes}
        return {**resident,
                "hit_bytes": _HIT_BYTES.value,
                "spill_bytes": _SPILL_BYTES.value,
                "evict_bytes": _EVICT_BYTES.value}

    def _update_gauges_locked(self) -> None:
        _CUR_BYTES.set(self._bytes)
        _CUR_ENTRIES.set(len(self._index))


def _unlink(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


_TIER = DiskTier()


def get_tier() -> DiskTier:
    return _TIER

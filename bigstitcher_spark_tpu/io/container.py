"""Fusion-container creation & metadata (CreateFusionContainer equivalent).

Creates the empty output container (N5 / OME-ZARR / HDF5, optionally as a
BDV-project layout), the multiresolution pyramid datasets, and persists all
fusion parameters as ``Bigstitcher-Spark/*`` root attributes — the persisted
config contract between ``create-fusion-container`` and ``affine-fusion``
(reference: CreateFusionContainer.java:302-320,462-516 ↔
SparkAffineFusion.java:239-309).

Dataset layouts (matching the reference so BigStitcher/BDV can open them):
  * plain N5/HDF5:  ``ch{c}tp{t}/s{level}``
  * BDV project:    ``setup{c}/timepoint{t}/s{level}``
  * OME-ZARR:       5-D ``/{level}`` datasets, logical xyzct (on-disk tczyx),
                    with OME-NGFF v0.4 ``multiscales`` metadata.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..utils.geometry import Interval
from .chunkstore import ChunkStore, StorageFormat

ATTR_PREFIX = "Bigstitcher-Spark"


def _epilogue_attr_key(dataset: str, ct) -> str:
    """Root-attribute key recording that the fusion epilogue materialized
    ``dataset`` for container slot (channel index, timepoint index). One
    FLAT key per (level, slot) — dataset path separators are folded so the
    key nests exactly one map under ``Bigstitcher-Spark/epilogue`` on
    JSON-attribute stores and stays a single attribute name on HDF5."""
    c, t = ct
    return (f"{ATTR_PREFIX}/epilogue/"
            f"{dataset.strip('/').replace('/', '.')}-c{c}t{t}")


def set_epilogue_written(store, dataset: str, ct, written: bool) -> None:
    """Record (or revoke) the fused-multiscale-epilogue marker for one
    pyramid level dataset and (channel, timepoint) slot. The downsample
    stage consults it (``downsample_pyramid_level(skip_existing=True)``)
    to skip levels the fusion drain already shipped — revoking on every
    non-epilogue fusion keeps a rerun from trusting stale levels."""
    store.set_attribute("", _epilogue_attr_key(dataset, ct), bool(written))


def epilogue_written(store, dataset: str, ct) -> bool:
    """Whether the fusion epilogue materialized ``dataset`` for this
    (channel, timepoint) slot."""
    return bool(store.get_attribute("", _epilogue_attr_key(dataset, ct),
                                    False))


@dataclass
class MultiResolutionLevelInfo:
    """Per-level dataset metadata (mvrecon ``MultiResolutionLevelInfo``)."""

    dataset: str
    dimensions: list[int]
    blockSize: list[int]
    relativeDownsampling: list[int]
    absoluteDownsampling: list[int]
    dataType: str

    def to_json(self) -> dict:
        return dict(
            dataset=self.dataset,
            dimensions=[int(v) for v in self.dimensions],
            blockSize=[int(v) for v in self.blockSize],
            relativeDownsampling=[int(v) for v in self.relativeDownsampling],
            absoluteDownsampling=[int(v) for v in self.absoluteDownsampling],
            dataType=self.dataType,
        )

    @staticmethod
    def from_json(d: dict) -> "MultiResolutionLevelInfo":
        return MultiResolutionLevelInfo(
            d["dataset"], d["dimensions"], d["blockSize"],
            d["relativeDownsampling"], d["absoluteDownsampling"], d["dataType"],
        )


@dataclass
class FusionContainerMeta:
    input_xml: str
    num_timepoints: int
    num_channels: int
    bbox: Interval
    data_type: str
    block_size: list[int]
    fusion_format: str  # "N5" | "OME-ZARR" | "HDF5" | "BDV/N5" | ...
    preserve_anisotropy: bool = False
    anisotropy_factor: float = float("nan")
    min_intensity: float | None = None
    max_intensity: float | None = None
    # [channel + t*numChannels][level]
    mr_infos: list[list[MultiResolutionLevelInfo]] = field(default_factory=list)


def estimate_multires_pyramid(
    dims: Sequence[int], anisotropy_factor: float = float("nan"),
    min_size: int = 64, max_levels: int = 8,
) -> list[list[int]]:
    """Propose absolute downsampling steps (role of
    ExportN5Api.estimateMultiResPyramid, CreateFusionContainer.java:263).
    Halve every axis still larger than ``min_size``; with preserved
    anisotropy (z thinner by ``anisotropy_factor``) z starts halving only
    once xy have caught up."""
    dims = [int(d) for d in dims]
    out = [[1, 1, 1]]
    cur = [1, 1, 1]
    aniso = anisotropy_factor if np.isfinite(anisotropy_factor) else 1.0
    for _ in range(max_levels - 1):
        step = [1, 1, 1]
        for d in range(3):
            eff = dims[d] // cur[d]
            if d == 2 and cur[2] * aniso > cur[0]:
                continue  # z is already coarser in world units
            if eff > min_size:
                step[d] = 2
        if step == [1, 1, 1]:
            break
        cur = [c * s for c, s in zip(cur, step)]
        out.append(list(cur))
    return out


def _relative_steps(absolute: list[list[int]]) -> list[list[int]]:
    rel = [list(absolute[0])]
    for i in range(1, len(absolute)):
        rel.append([absolute[i][d] // absolute[i - 1][d] for d in range(3)])
    return rel


def _level_dims(dims: Sequence[int], absolute: Sequence[int]) -> list[int]:
    # successive relative halving => floor division by the absolute factor
    return [max(1, int(d) // int(a)) for d, a in zip(dims, absolute)]


def create_fusion_container(
    out_path: str,
    storage_format: StorageFormat,
    input_xml: str,
    num_timepoints: int,
    num_channels: int,
    bbox: Interval,
    data_type: str = "float32",
    block_size: Sequence[int] = (128, 128, 128),
    downsamplings: list[list[int]] | None = None,
    compression: str = "zstd",
    bdv: bool = False,
    preserve_anisotropy: bool = False,
    anisotropy_factor: float = float("nan"),
    min_intensity: float | None = None,
    max_intensity: float | None = None,
    setup_id_offset: int = 0,
) -> FusionContainerMeta:
    """``setup_id_offset``: first BDV setup id to create — nonzero when
    appending a fusion into an existing BDV project (the next channel/tile
    setup ids, BDVSparkInstantiateViewSetup.java:57-112)."""
    if storage_format == StorageFormat.HDF5:
        return _create_fusion_container_hdf5(
            out_path, input_xml, num_timepoints, num_channels, bbox,
            data_type, block_size, downsamplings, compression, bdv,
            preserve_anisotropy, anisotropy_factor, min_intensity,
            max_intensity)
    store = ChunkStore.create(out_path, storage_format)
    dims = list(bbox.shape)
    if downsamplings is None:
        downsamplings = [[1, 1, 1]]
    rel = _relative_steps(downsamplings)
    block_size = [int(b) for b in block_size]
    dt = np.dtype(data_type).name

    if storage_format == StorageFormat.ZARR:
        fusion_format = "BDV/OME-ZARR" if bdv else "OME-ZARR"
    else:
        fusion_format = "BDV/N5" if bdv else "N5"

    mr_infos: list[list[MultiResolutionLevelInfo]] = []
    if storage_format == StorageFormat.ZARR:
        # one 5-D multiscale pyramid holds all channels/timepoints
        levels: list[MultiResolutionLevelInfo] = []
        for lvl, absd in enumerate(downsamplings):
            ldims = _level_dims(dims, absd)
            shape5 = ldims + [num_channels, num_timepoints]
            block5 = block_size + [1, 1]
            store.create_dataset(str(lvl), shape5, block5, dt,
                                 compression=compression, delete_existing=True)
            levels.append(MultiResolutionLevelInfo(
                dataset=f"/{lvl}", dimensions=shape5, blockSize=block5,
                relativeDownsampling=rel[lvl], absoluteDownsampling=list(absd),
                dataType=dt,
            ))
        for _ in range(num_channels * num_timepoints):
            mr_infos.append(levels)
        _write_ome_ngff_multiscales(store, downsamplings, anisotropy_factor)
    else:
        for t in range(num_timepoints):
            for c in range(num_channels):
                if bdv:
                    s_id = c + setup_id_offset
                    prefix = f"setup{s_id}/timepoint{t}"
                    store.set_attribute(f"setup{s_id}", "downsamplingFactors",
                                        [list(a) for a in downsamplings])
                    store.set_attribute(f"setup{s_id}", "dataType", dt)
                else:
                    prefix = f"ch{c}tp{t}"
                levels = []
                for lvl, absd in enumerate(downsamplings):
                    ldims = _level_dims(dims, absd)
                    ds = store.create_dataset(
                        f"{prefix}/s{lvl}", ldims, block_size, dt,
                        compression=compression, delete_existing=True,
                    )
                    store.set_attribute(ds.path, "downsamplingFactors",
                                        [int(v) for v in absd])
                    levels.append(MultiResolutionLevelInfo(
                        dataset=f"{prefix}/s{lvl}", dimensions=ldims,
                        blockSize=list(block_size),
                        relativeDownsampling=rel[lvl],
                        absoluteDownsampling=list(absd), dataType=dt,
                    ))
                # reference indexing: mrInfos[c + t*numChannels]
                mr_infos.append(levels)

    meta = FusionContainerMeta(
        input_xml=input_xml, num_timepoints=num_timepoints,
        num_channels=num_channels, bbox=bbox, data_type=dt,
        block_size=block_size, fusion_format=fusion_format,
        preserve_anisotropy=preserve_anisotropy,
        anisotropy_factor=anisotropy_factor,
        min_intensity=min_intensity, max_intensity=max_intensity,
        mr_infos=mr_infos,
    )
    write_container_meta(store, meta)
    return meta


def _create_fusion_container_hdf5(
    out_path, input_xml, num_timepoints, num_channels, bbox, data_type,
    block_size, downsamplings, compression, bdv, preserve_anisotropy,
    anisotropy_factor, min_intensity, max_intensity,
) -> FusionContainerMeta:
    """HDF5 fusion container, local-only (CreateFusionContainer.java:462-487;
    the local-only restriction mirrors :141-145). ``bdv=True`` writes the
    classic BigDataViewer cell layout (t{TTTTT}/s{SS}/{L}/cells plus
    per-setup resolutions/subdivisions tables) so BDV can open the file."""
    from .chunkstore import Hdf5Store

    if os.path.exists(out_path):
        os.remove(out_path)
    store = Hdf5Store(out_path, mode="w")
    dims = list(bbox.shape)
    if downsamplings is None:
        downsamplings = [[1, 1, 1]]
    rel = _relative_steps(downsamplings)
    block_size = [int(b) for b in block_size]
    dt = np.dtype(data_type).name
    if compression.split(":")[0] not in ("gzip", "raw"):
        compression = "gzip"  # h5py codec surface (N5Util HDF5 writer role)
    fusion_format = "BDV/HDF5" if bdv else "HDF5"

    if bdv:
        for c in range(num_channels):
            store.put_array(f"s{c:02d}/resolutions",
                            np.asarray(downsamplings, np.float64))
            store.put_array(f"s{c:02d}/subdivisions",
                            np.tile(np.asarray(block_size, np.int32),
                                    (len(downsamplings), 1)))
    mr_infos: list[list[MultiResolutionLevelInfo]] = []
    for t in range(num_timepoints):
        for c in range(num_channels):
            levels = []
            for lvl, absd in enumerate(downsamplings):
                ldims = _level_dims(dims, absd)
                path = (f"t{t:05d}/s{c:02d}/{lvl}/cells" if bdv
                        else f"ch{c}tp{t}/s{lvl}")
                store.create_dataset(path, ldims, block_size, dt,
                                     compression=compression,
                                     delete_existing=True)
                levels.append(MultiResolutionLevelInfo(
                    dataset=path, dimensions=ldims,
                    blockSize=list(block_size), relativeDownsampling=rel[lvl],
                    absoluteDownsampling=list(absd), dataType=dt,
                ))
            mr_infos.append(levels)

    meta = FusionContainerMeta(
        input_xml=input_xml, num_timepoints=num_timepoints,
        num_channels=num_channels, bbox=bbox, data_type=dt,
        block_size=block_size, fusion_format=fusion_format,
        preserve_anisotropy=preserve_anisotropy,
        anisotropy_factor=anisotropy_factor,
        min_intensity=min_intensity, max_intensity=max_intensity,
        mr_infos=mr_infos,
    )
    write_container_meta(store, meta)
    store.close()
    return meta


def open_container(path: str):
    """Open a fusion container root: HDF5 file or N5/ZARR directory/URI."""
    if (str(path).endswith((".h5", ".hdf5"))
            or (os.path.isfile(path) and not str(path).endswith(".xml"))):
        from .chunkstore import Hdf5Store

        return Hdf5Store(path, mode="a")
    return ChunkStore.open(path)


def write_container_meta(store: ChunkStore, meta: FusionContainerMeta) -> None:
    sa = lambda k, v: store.set_attribute("", f"{ATTR_PREFIX}/{k}", v)
    sa("FusionFormat", meta.fusion_format)
    sa("InputXML", meta.input_xml)
    sa("NumTimepoints", meta.num_timepoints)
    sa("NumChannels", meta.num_channels)
    sa("Boundingbox_min", list(meta.bbox.min))
    sa("Boundingbox_max", list(meta.bbox.max))
    sa("PreserveAnisotropy", meta.preserve_anisotropy)
    if meta.preserve_anisotropy and np.isfinite(meta.anisotropy_factor):
        sa("AnisotropyFactor", meta.anisotropy_factor)
    sa("DataType", meta.data_type)
    sa("BlockSize", meta.block_size)
    if meta.min_intensity is not None and meta.max_intensity is not None:
        sa("MinIntensity", meta.min_intensity)
        sa("MaxIntensity", meta.max_intensity)
    sa("MultiResolutionInfos",
       [[li.to_json() for li in levels] for levels in meta.mr_infos])


def read_container_meta(store: ChunkStore) -> FusionContainerMeta:
    ga = lambda k, d=None: store.get_attribute("", f"{ATTR_PREFIX}/{k}", d)
    fusion_format = ga("FusionFormat")
    if fusion_format is None:
        raise ValueError(
            "Could not load 'Bigstitcher-Spark/FusionFormat' metadata — "
            "run create-fusion-container first."
        )
    bbox = Interval(ga("Boundingbox_min"), ga("Boundingbox_max"))
    mr = [
        [MultiResolutionLevelInfo.from_json(li) for li in levels]
        for levels in ga("MultiResolutionInfos", [])
    ]
    return FusionContainerMeta(
        input_xml=ga("InputXML"),
        num_timepoints=int(ga("NumTimepoints")),
        num_channels=int(ga("NumChannels")),
        bbox=bbox,
        data_type=ga("DataType"),
        block_size=[int(v) for v in ga("BlockSize")],
        fusion_format=fusion_format,
        preserve_anisotropy=bool(ga("PreserveAnisotropy", False)),
        anisotropy_factor=float(ga("AnisotropyFactor", float("nan"))),
        min_intensity=ga("MinIntensity"),
        max_intensity=ga("MaxIntensity"),
        mr_infos=mr,
    )


def _write_ome_ngff_multiscales(
    store: ChunkStore, downsamplings: list[list[int]], anisotropy_factor: float,
) -> None:
    """OME-NGFF v0.4 multiscales metadata (CreateFusionContainer.java:368-388).
    Axes listed in on-disk (tczyx) order."""
    aniso = anisotropy_factor if np.isfinite(anisotropy_factor) else 1.0
    res0 = [1.0, 1.0, aniso]  # xyz
    datasets = []
    for lvl, absd in enumerate(downsamplings):
        scale_xyz = [res0[d] * absd[d] for d in range(3)]
        trans_xyz = [0.5 * (absd[d] - 1) * res0[d] for d in range(3)]
        datasets.append({
            "path": str(lvl),
            "coordinateTransformations": [
                {"type": "scale",
                 "scale": [1.0, 1.0] + scale_xyz[::-1]},
                {"type": "translation",
                 "translation": [0.0, 0.0] + trans_xyz[::-1]},
            ],
        })
    store.set_attribute("", "multiscales", [{
        "version": "0.4",
        "name": "/",
        "axes": [
            {"name": "t", "type": "time", "unit": "second"},
            {"name": "c", "type": "channel"},
            {"name": "z", "type": "space", "unit": "micrometer"},
            {"name": "y", "type": "space", "unit": "micrometer"},
            {"name": "x", "type": "space", "unit": "micrometer"},
        ],
        "datasets": datasets,
        "type": "sampling",
    }])

"""SpimData2 project model: BigStitcher-compatible XML load/save.

The XML project file is the shared state of the whole pipeline (reference:
spim_data + mvrecon ``SpimData2``/``XmlIoSpimData2``, loaded per stage at
AbstractBasic.java:49-70 and per executor at util/Spark.java:243-265). This
module re-implements the project model natively: view setups with
angle/channel/illumination/tile attributes, per-view affine transform chains,
missing views, interest-point lookups, bounding boxes, and stitching results.

Element shapes follow the spim_data XML schema (SpimData version="0.2") so the
BigStitcher GUI remains the oracle for our outputs. Unknown sections and
unknown image-loader formats are preserved verbatim on round-trip.

Axis order: xyz everywhere; affines are 3x4 row-major (see utils.geometry).
A transform chain's FIRST element is the OUTERMOST (last-applied) transform,
matching ``ViewRegistration.getModel()`` semantics.
"""

from __future__ import annotations

import copy
import os
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np

from ..utils.geometry import (
    Interval,
    affine_from_flat,
    affine_to_flat,
    concatenate_all,
    identity_affine,
)

VIEW_ATTRIBUTES = ("illumination", "channel", "tile", "angle")
# XML element tag per attribute name inside <Attributes name="...">
_ATTR_TAG = {
    "illumination": "Illumination",
    "channel": "Channel",
    "tile": "Tile",
    "angle": "Angle",
}


@dataclass(frozen=True, order=True)
class ViewId:
    timepoint: int
    setup: int

    def __str__(self):
        return f"(tp={self.timepoint}, setup={self.setup})"


@dataclass
class AttributeEntity:
    id: int
    name: str
    # tile location (3 doubles) / angle rotation axis+degrees, when present
    extra: dict = field(default_factory=dict)


@dataclass
class ViewSetup:
    id: int
    name: str
    size: tuple[int, int, int]  # xyz
    voxel_unit: str = "um"
    voxel_size: tuple[float, float, float] = (1.0, 1.0, 1.0)
    attributes: dict[str, int] = field(default_factory=dict)  # attr name -> entity id


@dataclass
class ViewTransform:
    name: str
    affine: np.ndarray  # 3x4

    def copy(self) -> "ViewTransform":
        return ViewTransform(self.name, self.affine.copy())


@dataclass
class ImageLoader:
    """Structured for known formats; raw element preserved otherwise."""

    format: str = "bdv.n5"
    path: str = "dataset.n5"  # relative to the XML, or absolute
    path_type: str = "relative"
    raw: ET.Element | None = None  # verbatim passthrough for unknown formats


@dataclass
class InterestPointLookup:
    """Pointer from the XML into interestpoints.n5 (one label of one view)."""

    label: str
    params: str = ""
    path: str = ""  # group inside interestpoints.n5, e.g. tpId_0_viewSetupId_1/beads


@dataclass
class PairwiseStitchingResult:
    """A pairwise shift between two view groups (SparkPairwiseStitching output).

    ``transform`` is the 3x4 affine mapping group A into group B's space
    (translation-only for phase correlation); ``hash`` ties the result to the
    registrations it was computed against so the solver can reject stale links
    (reference: Spark.java:201-233, SparkPairwiseStitching.java:287-299).
    """

    views_a: tuple[ViewId, ...]
    views_b: tuple[ViewId, ...]
    transform: np.ndarray  # 3x4
    correlation: float
    hash: float = 0.0
    bbox: Interval | None = None

    @property
    def pair_key(self) -> tuple:
        return (self.views_a, self.views_b)


def registration_hash(transforms_a: Sequence[np.ndarray], transforms_b: Sequence[np.ndarray]) -> float:
    """Stable scalar fingerprint of the registrations a stitching result was
    computed under (role of ``PairwiseStitchingResult.getHash()``)."""
    h = 0.0
    for m in list(transforms_a) + list(transforms_b):
        h += float(np.sum(np.asarray(m, dtype=np.float64) * np.arange(1, 13).reshape(3, 4)))
    return h


class SpimData:
    """The project: sequence description + registrations + derived state."""

    def __init__(self):
        self.base_path: str = "."
        self.image_loader: ImageLoader = ImageLoader()
        self.setups: dict[int, ViewSetup] = {}
        # attribute name -> {entity id -> entity}
        self.attributes: dict[str, dict[int, AttributeEntity]] = {
            a: {} for a in VIEW_ATTRIBUTES
        }
        self.timepoints: list[int] = [0]
        self.missing_views: set[ViewId] = set()
        self.registrations: dict[ViewId, list[ViewTransform]] = {}
        self.interest_points: dict[ViewId, dict[str, InterestPointLookup]] = {}
        self.bounding_boxes: dict[str, Interval] = {}
        self.stitching_results: dict[tuple, PairwiseStitchingResult] = {}
        # virtual split views: new setup id -> (source setup id, pixel offset)
        # (role of the reference's SplitViewerImgLoader; models.splitting)
        self.split_info: dict[int, tuple[int, tuple[int, int, int]]] = {}
        self._unknown_sections: list[ET.Element] = []
        self.xml_path: str | None = None  # where this project was loaded from

    # ------------------------------------------------------------------ views

    def view_ids(self, include_missing: bool = False) -> list[ViewId]:
        out = [
            ViewId(t, s)
            for t in self.timepoints
            for s in sorted(self.setups)
        ]
        if not include_missing:
            out = [v for v in out if v not in self.missing_views]
        return out

    def view_size(self, view: ViewId) -> tuple[int, int, int]:
        return self.setups[view.setup].size

    def model(self, view: ViewId) -> np.ndarray:
        """Full pixel->world affine of a view (concatenated chain)."""
        chain = self.registrations.get(view)
        if not chain:
            return identity_affine()
        return concatenate_all([t.affine for t in chain])

    def preconcatenate_transform(self, view: ViewId, t: ViewTransform) -> None:
        """Add a transform applied AFTER everything else (prepend to chain)."""
        self.registrations.setdefault(view, []).insert(0, t)

    def setup_attribute(self, setup_id: int, attr: str) -> int:
        return self.setups[setup_id].attributes.get(attr, 0)

    # ------------------------------------------------------------------- load

    @staticmethod
    def load(path: str | os.PathLike) -> "SpimData":
        """Load a project XML from a local path or cloud URI (the reference
        loads XMLs from file/S3/GCS via URITools, AbstractBasic.java:49-70)."""
        path = str(path)
        from . import uris

        if uris.has_scheme(path):
            root = ET.fromstring(uris.read_bytes(path).decode())
        else:
            path = uris.strip_file_scheme(path)
            root = ET.parse(path).getroot()
        if root.tag != "SpimData":
            raise ValueError(f"not a SpimData XML: root tag {root.tag!r}")
        sd = SpimData()
        sd.xml_path = path

        bp = root.find("BasePath")
        if bp is not None:
            sd.base_path = bp.text or "."

        seq = root.find("SequenceDescription")
        if seq is None:
            raise ValueError("missing SequenceDescription")
        sd._parse_sequence(seq)

        vr = root.find("ViewRegistrations")
        if vr is not None:
            for el in vr.findall("ViewRegistration"):
                vid = ViewId(int(el.get("timepoint")), int(el.get("setup")))
                chain = []
                for t in el.findall("ViewTransform"):
                    name_el = t.find("Name")
                    aff_el = t.find("affine")
                    chain.append(
                        ViewTransform(
                            name_el.text if name_el is not None else "",
                            affine_from_flat(aff_el.text.split()),
                        )
                    )
                sd.registrations[vid] = chain

        vip = root.find("ViewInterestPoints")
        if vip is not None:
            for el in vip.findall("ViewInterestPointsFile"):
                vid = ViewId(int(el.get("timepoint")), int(el.get("setup")))
                label = el.get("label")
                sd.interest_points.setdefault(vid, {})[label] = InterestPointLookup(
                    label=label,
                    params=el.get("params", ""),
                    path=(el.text or "").strip(),
                )

        bbs = root.find("BoundingBoxes")
        if bbs is not None:
            for el in bbs.findall("BoundingBoxDefinition"):
                mn = [int(v) for v in el.find("min").text.split()]
                mx = [int(v) for v in el.find("max").text.split()]
                sd.bounding_boxes[el.get("name")] = Interval(mn, mx)

        sr = root.find("StitchingResults")
        if sr is not None:
            for el in sr.findall("PairwiseResult"):
                res = _parse_pairwise_result(el)
                sd.stitching_results[res.pair_key] = res

        si = root.find("SplitInfo")
        if si is not None:
            for el in si.findall("Split"):
                sd.split_info[int(el.get("setup"))] = (
                    int(el.get("source")),
                    tuple(int(v) for v in el.get("offset").split()),
                )

        known = {
            "BasePath", "SequenceDescription", "ViewRegistrations",
            "ViewInterestPoints", "BoundingBoxes", "StitchingResults",
            "SplitInfo",
        }
        for child in root:
            if child.tag not in known:
                sd._unknown_sections.append(copy.deepcopy(child))
        return sd

    def _parse_sequence(self, seq: ET.Element) -> None:
        il = seq.find("ImageLoader")
        if il is not None:
            fmt = il.get("format", "")
            loader = ImageLoader(format=fmt, raw=copy.deepcopy(il))
            for tag in ("n5", "zarr", "hdf5", "ome.zarr"):
                sub = il.find(tag)
                if sub is not None:
                    loader.path = (sub.text or "").strip()
                    loader.path_type = sub.get("type", "relative")
                    break
            self.image_loader = loader

        vss = seq.find("ViewSetups")
        if vss is not None:
            for el in vss.findall("ViewSetup"):
                vs = ViewSetup(
                    id=int(el.findtext("id")),
                    name=el.findtext("name", default=""),
                    size=tuple(int(v) for v in el.findtext("size", default="0 0 0").split()),
                )
                vox = el.find("voxelSize")
                if vox is not None:
                    vs.voxel_unit = vox.findtext("unit", default="um")
                    vs.voxel_size = tuple(
                        float(v) for v in vox.findtext("size", default="1 1 1").split()
                    )
                attrs = el.find("attributes")
                if attrs is not None:
                    for a in attrs:
                        vs.attributes[a.tag] = int(a.text)
                self.setups[vs.id] = vs
            for el in vss.findall("Attributes"):
                name = el.get("name")
                table = self.attributes.setdefault(name, {})
                for ent in el:
                    eid = int(ent.findtext("id"))
                    ename = ent.findtext("name", default=str(eid))
                    extra = {}
                    for sub in ent:
                        if sub.tag not in ("id", "name"):
                            extra[sub.tag] = sub.text
                    table[eid] = AttributeEntity(eid, ename, extra)

        tps = seq.find("Timepoints")
        if tps is not None:
            ttype = tps.get("type", "pattern")
            if ttype == "pattern":
                self.timepoints = _parse_integer_pattern(
                    tps.findtext("integerpattern", default="0")
                )
            elif ttype == "range":
                first = int(tps.findtext("first", default="0"))
                last = int(tps.findtext("last", default="0"))
                self.timepoints = list(range(first, last + 1))
            else:
                raise ValueError(f"unsupported Timepoints type {ttype!r}")

        mv = seq.find("MissingViews")
        if mv is not None:
            for el in mv.findall("MissingView"):
                self.missing_views.add(
                    ViewId(int(el.get("timepoint")), int(el.get("setup")))
                )

    # ------------------------------------------------------------------- save

    def save(self, path: str | os.PathLike | None = None) -> None:
        if path is None:
            path = self.xml_path
        if path is None:
            raise ValueError("no path to save to")
        path = str(path)
        root = ET.Element("SpimData", version="0.2")
        bp = ET.SubElement(root, "BasePath", type="relative")
        bp.text = self.base_path

        seq = ET.SubElement(root, "SequenceDescription")
        self._write_sequence(seq)

        vr = ET.SubElement(root, "ViewRegistrations")
        for vid in sorted(self.registrations):
            el = ET.SubElement(
                vr, "ViewRegistration",
                timepoint=str(vid.timepoint), setup=str(vid.setup),
            )
            for t in self.registrations[vid]:
                tel = ET.SubElement(el, "ViewTransform", type="affine")
                ET.SubElement(tel, "Name").text = t.name
                ET.SubElement(tel, "affine").text = " ".join(
                    repr(v) for v in affine_to_flat(t.affine)
                )

        vip = ET.SubElement(root, "ViewInterestPoints")
        for vid in sorted(self.interest_points):
            for label, lk in sorted(self.interest_points[vid].items()):
                el = ET.SubElement(
                    vip, "ViewInterestPointsFile",
                    timepoint=str(vid.timepoint), setup=str(vid.setup),
                    label=label, params=lk.params,
                )
                el.text = lk.path

        bbs = ET.SubElement(root, "BoundingBoxes")
        for name, box in sorted(self.bounding_boxes.items()):
            el = ET.SubElement(bbs, "BoundingBoxDefinition", name=name)
            ET.SubElement(el, "min").text = " ".join(str(v) for v in box.min)
            ET.SubElement(el, "max").text = " ".join(str(v) for v in box.max)

        preserved = {el.tag: el for el in self._unknown_sections}
        root.append(copy.deepcopy(preserved.pop(
            "PointSpreadFunctions", ET.Element("PointSpreadFunctions"))))

        sr = ET.SubElement(root, "StitchingResults")
        for res in self.stitching_results.values():
            sr.append(_pairwise_result_to_xml(res))

        root.append(copy.deepcopy(preserved.pop(
            "IntensityAdjustments", ET.Element("IntensityAdjustments"))))

        if self.split_info:
            si = ET.SubElement(root, "SplitInfo")
            for setup, (src, off) in sorted(self.split_info.items()):
                ET.SubElement(si, "Split", setup=str(setup), source=str(src),
                              offset=" ".join(str(v) for v in off))

        for el in preserved.values():
            root.append(copy.deepcopy(el))

        ET.indent(root)
        from . import uris

        if uris.has_scheme(path):
            buf = ET.tostring(root, encoding="unicode", xml_declaration=True)
            uris.write_bytes(path, buf.encode())
        else:
            path = uris.strip_file_scheme(path)
            ET.ElementTree(root).write(path, encoding="unicode",
                                       xml_declaration=True)
        self.xml_path = path

    def _write_sequence(self, seq: ET.Element) -> None:
        il = self.image_loader
        known = {"bdv.n5", "bdv.zarr", "bdv.hdf5"}
        if il.raw is not None and il.format not in known:
            seq.append(copy.deepcopy(il.raw))
        else:
            el = ET.SubElement(seq, "ImageLoader", format=il.format, version="1.0")
            tag = {"bdv.n5": "n5", "bdv.zarr": "zarr", "bdv.hdf5": "hdf5"}.get(
                il.format, "n5"
            )
            sub = ET.SubElement(el, tag, type=il.path_type)
            sub.text = il.path

        vss = ET.SubElement(seq, "ViewSetups")
        for sid in sorted(self.setups):
            vs = self.setups[sid]
            el = ET.SubElement(vss, "ViewSetup")
            ET.SubElement(el, "id").text = str(vs.id)
            ET.SubElement(el, "name").text = vs.name or str(vs.id)
            ET.SubElement(el, "size").text = " ".join(str(v) for v in vs.size)
            vox = ET.SubElement(el, "voxelSize")
            ET.SubElement(vox, "unit").text = vs.voxel_unit
            ET.SubElement(vox, "size").text = " ".join(repr(float(v)) for v in vs.voxel_size)
            attrs = ET.SubElement(el, "attributes")
            attr_names = list(VIEW_ATTRIBUTES) + [
                a for a in vs.attributes if a not in VIEW_ATTRIBUTES
            ]
            for a in attr_names:
                ET.SubElement(attrs, a).text = str(vs.attributes.get(a, 0))
        all_tables = list(VIEW_ATTRIBUTES) + [
            n for n in self.attributes if n not in VIEW_ATTRIBUTES
        ]
        for name in all_tables:
            table = self.attributes.get(name, {})
            el = ET.SubElement(vss, "Attributes", name=name)
            for eid in sorted(table):
                ent = table[eid]
                tag = _ATTR_TAG.get(name, name.capitalize())
                sub = ET.SubElement(el, tag)
                ET.SubElement(sub, "id").text = str(ent.id)
                ET.SubElement(sub, "name").text = ent.name
                for k, v in ent.extra.items():
                    ET.SubElement(sub, k).text = v

        tps = ET.SubElement(seq, "Timepoints", type="pattern")
        ET.SubElement(tps, "integerpattern").text = _format_integer_pattern(self.timepoints)

        mv = ET.SubElement(seq, "MissingViews")
        for vid in sorted(self.missing_views):
            ET.SubElement(
                mv, "MissingView",
                timepoint=str(vid.timepoint), setup=str(vid.setup),
            )

    # ---------------------------------------------------------------- helpers

    def remap_setup_ids(self, mapping: dict[int, int]) -> None:
        """Renumber ViewSetups (and every per-view table keyed by setup id)
        by ``mapping`` — acquisition-order remapping
        (SetupIDMapper.java:36-107). Ids not in the map are kept.

        Must run BEFORE registration artifacts exist: interest points live in
        interestpoints.n5 groups named by setup id, and stitching results
        key pairs by ViewId — renumbering under them would silently re-attach
        data to the wrong physical tiles."""
        if self.interest_points or self.stitching_results:
            raise ValueError(
                "remap_setup_ids must run before detection/stitching: the "
                "project already has interest points or stitching results "
                "keyed by the old setup ids (clear them first)")
        m = lambda s: mapping.get(s, s)
        import dataclasses

        self.setups = {
            m(s): dataclasses.replace(vs, id=m(s))
            for s, vs in self.setups.items()
        }
        self.registrations = {
            ViewId(v.timepoint, m(v.setup)): t
            for v, t in self.registrations.items()
        }
        self.interest_points = {
            ViewId(v.timepoint, m(v.setup)): t
            for v, t in self.interest_points.items()
        }
        self.missing_views = {
            ViewId(v.timepoint, m(v.setup)) for v in self.missing_views
        }
        self.split_info = {m(s): v for s, v in self.split_info.items()}

    def resolve_loader_path(self) -> str:
        from . import uris

        lp = self.image_loader.path
        if (self.image_loader.path_type == "absolute" or os.path.isabs(lp)
                or uris.has_scheme(lp)):
            return lp
        base = uris.dirname(self.xml_path or ".")
        return uris.normpath(uris.join(base, self.base_path, lp))


def _parse_integer_pattern(pattern: str) -> list[int]:
    out: list[int] = []
    for part in pattern.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part[1:]:
            a, rest = part.split("-", 1)
            step = 1
            if ":" in rest:  # spim_data TimePointsPattern "a-b:step"
                rest, s = rest.split(":", 1)
                step = int(s)
            out.extend(range(int(a), int(rest) + 1, step))
        else:
            out.append(int(part))
    return sorted(set(out)) or [0]


def _format_integer_pattern(tps: list[int]) -> str:
    tps = sorted(set(tps))
    if len(tps) > 1 and tps == list(range(tps[0], tps[-1] + 1)):
        return f"{tps[0]}-{tps[-1]}"
    return ",".join(str(t) for t in tps)


def _views_attr(views: Iterable[ViewId]) -> str:
    return ";".join(f"{v.timepoint},{v.setup}" for v in views)


def _parse_views_attr(s: str) -> tuple[ViewId, ...]:
    return tuple(
        ViewId(int(a), int(b))
        for a, b in (p.split(",") for p in s.split(";") if p)
    )


def _pairwise_result_to_xml(res: PairwiseStitchingResult) -> ET.Element:
    el = ET.Element(
        "PairwiseResult",
        views_a=_views_attr(res.views_a),
        views_b=_views_attr(res.views_b),
        hash=repr(res.hash),
    )
    ET.SubElement(el, "shift").text = " ".join(repr(v) for v in affine_to_flat(res.transform))
    ET.SubElement(el, "correlation").text = repr(float(res.correlation))
    if res.bbox is not None:
        ET.SubElement(el, "bbox_min").text = " ".join(str(v) for v in res.bbox.min)
        ET.SubElement(el, "bbox_max").text = " ".join(str(v) for v in res.bbox.max)
    return el


def _parse_pairwise_result(el: ET.Element) -> PairwiseStitchingResult:
    bbox = None
    if el.find("bbox_min") is not None:
        bbox = Interval(
            [int(v) for v in el.findtext("bbox_min").split()],
            [int(v) for v in el.findtext("bbox_max").split()],
        )
    return PairwiseStitchingResult(
        views_a=_parse_views_attr(el.get("views_a")),
        views_b=_parse_views_attr(el.get("views_b")),
        transform=affine_from_flat(el.findtext("shift").split()),
        correlation=float(el.findtext("correlation", default="0")),
        hash=float(el.get("hash", "0")),
        bbox=bbox,
    )

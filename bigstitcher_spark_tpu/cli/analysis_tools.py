"""CLI for the static analyzer (``bst lint``) and the runtime-config
registry (``bst config``).

``bst lint`` is the interactive face of the tier-1 gate
(tests/test_lint.py, scripts/lint.sh): same checks, same baseline, so a
clean ``bst lint`` means the tier-1 lint test passes. ``bst config``
renders the full resolved ``BST_*`` surface — the reference's
spark-defaults/--conf visibility, which previously required reading 22
scattered env accesses."""

from __future__ import annotations

import json as _json
import sys
from pathlib import Path

import click


@click.command()
@click.option("--root", type=click.Path(exists=True, file_okay=False),
              default=None,
              help="package tree to scan (default: the installed "
                   "bigstitcher_spark_tpu package)")
@click.option("--baseline", "baseline_path", type=click.Path(), default=None,
              help="baseline JSON (default: <root>/analysis/baseline.json)")
@click.option("--fail-on-new/--no-fail-on-new", default=True,
              show_default=True,
              help="exit 1 when any non-baselined finding exists")
@click.option("--all", "show_all", is_flag=True,
              help="also print baselined (legacy) findings")
@click.option("--update-baseline", is_flag=True,
              help="rewrite the baseline to the current findings")
@click.option("--check", "only_checks", multiple=True,
              help="run only these checks (repeatable); default: all")
@click.option("--graph", "graph_name",
              type=click.Choice(["lock-order"]), default=None,
              help="dump a check's computed graph as DOT instead of "
                   "linting (lock-order: the whole-package lock-"
                   "acquisition graph, for verifying cycle findings)")
def lint_cmd(root, baseline_path, fail_on_new, show_all, update_baseline,
             only_checks, graph_name):
    """Run the AST invariant analyzer over the package.

    Checks: host-sync (no hidden device round-trips in ops/ and models/),
    lock-discipline (guarded state mutated lock-free), lock-order
    (cycles in the interprocedural lock-acquisition graph — potential
    deadlocks; dump the graph with --graph lock-order),
    blocking-under-lock (socket/queue/subprocess/device waits while a
    lock is held), thread-spawn (raw Thread/ThreadPoolExecutor outside
    utils/threads.py drop contextvars + cancel token), cancel-coverage
    (unbounded worker loops must poll cancellation), socket-hygiene
    (close() without shutdown() leaves phantom connections),
    config-registry (no raw BST_* environment access outside
    config.py), env-mutation (no BST_* environment WRITES anywhere — a
    multi-job daemon shares one env; per-job values go through
    config.overrides), metric-name / span-name (every bst_* series and
    span literal declared once in observe/metric_names.py). Suppress a
    single line with `# bst-lint: off=<check>` plus the justification."""
    from ..analysis import (
        ALL_CHECKS,
        default_baseline_path,
        default_root,
        load_baseline,
        lock_graph_dot,
        new_findings,
        parse_package,
        run_lint,
        save_baseline,
    )

    root = Path(root) if root else default_root()
    baseline_path = (Path(baseline_path) if baseline_path
                     else default_baseline_path(root))
    if graph_name is not None:
        ctxs, _suppressions, _errors = parse_package(root)
        click.echo(lock_graph_dot(ctxs), nl=False)
        return
    checks = None
    if only_checks:
        unknown = set(only_checks) - set(ALL_CHECKS)
        if unknown:
            raise click.ClickException(
                f"unknown check(s) {sorted(unknown)}; "
                f"available: {sorted(ALL_CHECKS)}")
        checks = {k: ALL_CHECKS[k] for k in only_checks}
    if update_baseline and only_checks:
        # a partial scan must not rewrite the whole-package baseline:
        # it would silently drop every other check's tracked entries
        # and fail the next full tier-1 run on untouched code
        raise click.ClickException(
            "--update-baseline needs a full scan; drop --check")
    findings = run_lint(root, checks=checks)
    if update_baseline:
        save_baseline(baseline_path, findings)
        click.echo(f"baseline updated: {len(findings)} finding(s) -> "
                   f"{baseline_path}")
        return
    baseline = load_baseline(baseline_path)
    new = new_findings(findings, baseline)
    shown = findings if show_all else new
    newset = {id(f) for f in new}
    for f in shown:
        tag = "" if id(f) in newset else " (baselined)"
        click.echo(f.render() + tag)
    legacy = len(findings) - len(new)
    click.echo(f"bst lint: {len(new)} new finding(s), "
               f"{legacy} baselined, {len(findings)} total")
    if new and fail_on_new:
        sys.exit(1)


@click.command()
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable resolved config")
@click.option("--verbose", "-v", is_flag=True,
              help="include type, default, consumer and docs per knob")
def config_cmd(as_json, verbose):
    """Dump every BST_* knob with its resolved value and source.

    One declaration per variable lives in bigstitcher_spark_tpu/config.py
    (name, type, default, doc); values are read from the environment at
    call time, `(env)` marks the ones the environment overrides."""
    from .. import config

    if as_json:
        click.echo(_json.dumps(config.resolve(), indent=1, default=str))
    else:
        click.echo(config.describe(verbose=verbose))

"""CLI entry point: one subcommand per pipeline stage, names matching the
reference's installed shell wrappers (install:122-139) so users of
BigStitcher-Spark can switch 1:1.

Run: ``python -m bigstitcher_spark_tpu.cli.main <tool> [options]``
"""

from __future__ import annotations

import click

from . import (
    analysis_tools,
    detection_tools,
    fusion_tools,
    intensity_tools,
    observe_tools,
    pipeline_tools,
    resave_tools,
    serve_tools,
    solver_tools,
    stitching_tools,
    telemetry_tools,
    tune_tools,
    utility_tools,
)


# tools that must NOT auto-bind the BST_METRICS_PORT exporter: daemon
# management and thin clients run on the same host as the daemon that
# owns the port (the `bst serve --detach` parent or a `bst submit` would
# steal it for milliseconds and break the resident daemon's bind), and
# the short diagnostic tools have nothing live to export. The daemon
# itself starts its exporter inside Daemon.start().
_NO_LIVE_EXPORTER = {"serve", "submit", "jobs", "cancel", "top",
                     "trace-dump", "history", "perf-diff", "config",
                     "env", "lint", "telemetry-merge", "trace-report",
                     "tune"}


@click.group()
@click.pass_context
def cli(ctx):
    """TPU-native BigStitcher: distributed stitching & fusion tools."""
    # multi-host bootstrap: no-op unless BST_COORDINATOR/BST_NUM_PROCESSES/
    # BST_PROCESS_ID (or BST_DISTRIBUTED=1 on an autodetecting pod) are
    # set. The telemetry relay (BST_TELEMETRY_RELAY) rides along for
    # workload tools only — a short `bst submit`/`bst jobs` has nothing
    # live to push, and a `bst serve` daemon hosts the collector itself
    # inside Daemon.start()
    from ..parallel.distributed import init_distributed

    init_distributed(
        start_relay=ctx.invoked_subcommand not in _NO_LIVE_EXPORTER)
    # live HTTP exporter for long one-shot runs: no-op unless
    # BST_METRICS_PORT is set (the serve daemon wires richer providers in)
    if ctx.invoked_subcommand not in _NO_LIVE_EXPORTER:
        from ..observe import httpexport

        httpexport.ensure_started()


cli.add_command(fusion_tools.create_fusion_container_cmd, "create-fusion-container")
cli.add_command(fusion_tools.affine_fusion_cmd, "affine-fusion")
cli.add_command(resave_tools.resave_cmd, "resave")
cli.add_command(resave_tools.downsample_cmd, "downsample")
cli.add_command(stitching_tools.stitching_cmd, "stitching")
cli.add_command(solver_tools.solver_cmd, "solver")
cli.add_command(detection_tools.detect_interestpoints_cmd, "detect-interestpoints")
cli.add_command(detection_tools.match_interestpoints_cmd, "match-interestpoints")
cli.add_command(fusion_tools.nonrigid_fusion_cmd, "nonrigid-fusion")
cli.add_command(utility_tools.clear_interestpoints_cmd, "clear-interestpoints")
cli.add_command(utility_tools.clear_registrations_cmd, "clear-registrations")
cli.add_command(utility_tools.transform_points_cmd, "transform-points")
cli.add_command(utility_tools.split_images_cmd, "split-images")
cli.add_command(intensity_tools.match_intensities_cmd, "match-intensities")
cli.add_command(intensity_tools.solve_intensities_cmd, "solve-intensities")
cli.add_command(utility_tools.inspect_interestpoints_cmd, "inspect-interestpoints")
cli.add_command(utility_tools.map_setup_ids_cmd, "map-setup-ids")
cli.add_command(utility_tools.env_cmd, "env")
cli.add_command(utility_tools.serve_container_cmd, "serve-container")
cli.add_command(telemetry_tools.telemetry_merge_cmd, "telemetry-merge")
cli.add_command(telemetry_tools.trace_report_cmd, "trace-report")
cli.add_command(analysis_tools.lint_cmd, "lint")
cli.add_command(analysis_tools.config_cmd, "config")
cli.add_command(serve_tools.serve_cmd, "serve")
cli.add_command(serve_tools.submit_cmd, "submit")
cli.add_command(serve_tools.jobs_cmd, "jobs")
cli.add_command(serve_tools.cancel_cmd, "cancel")
cli.add_command(pipeline_tools.pipeline_cmd, "pipeline")
cli.add_command(observe_tools.top_cmd, "top")
cli.add_command(observe_tools.trace_dump_cmd, "trace-dump")
cli.add_command(observe_tools.history_cmd, "history")
cli.add_command(observe_tools.perf_diff_cmd, "perf-diff")
cli.add_command(tune_tools.tune_cmd, "tune")


def main():
    cli(prog_name="bst")


if __name__ == "__main__":
    main()

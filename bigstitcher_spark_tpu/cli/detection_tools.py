"""CLI: detect-interestpoints, match-interestpoints, clear-interestpoints
(reference tools SparkInterestPointDetection / SparkGeometricDescriptorMatching
/ ClearInterestPoints)."""

from __future__ import annotations

import click

from .common import (
    infrastructure_options,
    load_project,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("-l", "--label", default="beads", help="interest point label")
@click.option("-s", "--sigma", default=1.8, type=float,
              help="DoG sigma (at detection resolution)")
@click.option("-t", "--threshold", default=0.008, type=float,
              help="DoG response threshold")
@click.option("-dsxy", "--downsampleXY", "downsample_xy", default=2, type=int)
@click.option("-dsz", "--downsampleZ", "downsample_z", default=1, type=int)
@click.option("-i0", "--minIntensity", "min_intensity", default=None,
              type=float)
@click.option("-i1", "--maxIntensity", "max_intensity", default=None,
              type=float)
@click.option("--localization", default="QUADRATIC",
              type=click.Choice(["NONE", "QUADRATIC"]),
              help="subpixel localization method")
@click.option("--onlyCompareOverlapTiles", "only_tiles", is_flag=True,
              default=False,
              help="with --overlappingOnly, test overlap only against views "
                   "of the same timepoint+channel (i.e. across tiles)")
@click.option("--prefetch", is_flag=True, default=False,
              help="accepted for reference compatibility; chunk prefetch is "
                   "always on (double-buffered host IO)")
@click.option("--type", "extrema", default="MAX",
              type=click.Choice(["MAX", "MIN", "BOTH"]),
              help="detect maxima, minima or both")
@click.option("--overlappingOnly", "overlapping_only", is_flag=True,
              help="only detect in regions overlapping other selected views")
@click.option("--maxSpots", "max_spots", default=0, type=int,
              help="keep only the brightest N spots per view (0 = all)")
@click.option("--maxSpotsPerOverlap", "max_spots_per_overlap", is_flag=True,
              help="distribute --maxSpots over overlap regions by volume")
@click.option("--keepTemporaryN5", "keep_temporary_n5", is_flag=True,
              default=False, expose_value=False,
              help="accepted for compatibility: this implementation compacts detections on device and never stages a temporary N5")
@click.option("--storeIntensities", "store_intensities", is_flag=True,
              help="sample + store per-point image intensities")
@click.option("--medianFilter", "median_radius", default=0, type=int,
              help="background-divide by per-slice median of this radius (0=off)")
@click.option("--blockSize", "block_size", default="512,512,128",
              help="detection block size at detection resolution")
def detect_interestpoints_cmd(xml, dry_run, **kw):
    """Distributed DoG interest-point detection (SparkInterestPointDetection)."""
    from ..io.dataset_io import ViewLoader
    from ..io.interestpoints import InterestPointStore
    from ..models.detection import (
        DetectionParams,
        detect_interest_points,
        save_detections,
    )
    from .common import parse_csv_ints

    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    params = DetectionParams(
        label=kw["label"], sigma=kw["sigma"], threshold=kw["threshold"],
        downsample_xy=kw["downsample_xy"], downsample_z=kw["downsample_z"],
        min_intensity=kw["min_intensity"], max_intensity=kw["max_intensity"],
        find_max=kw["extrema"] in ("MAX", "BOTH"),
        find_min=kw["extrema"] in ("MIN", "BOTH"),
        overlapping_only=kw["overlapping_only"],
        localization=kw["localization"],
        only_compare_overlap_tiles=kw["only_tiles"],
        max_spots=kw["max_spots"],
        max_spots_per_overlap=kw["max_spots_per_overlap"],
        store_intensities=kw["store_intensities"],
        median_radius=kw["median_radius"],
        block_size=tuple(parse_csv_ints(kw["block_size"], 3)),
    )
    loader = ViewLoader(sd)
    detections = detect_interest_points(sd, loader, views, params)
    total = sum(len(d.points) for d in detections)
    click.echo(f"detected {total} interest points over {len(detections)} views")
    if dry_run:
        click.echo("dryRun: not saving")
        return
    store = InterestPointStore.for_project(sd)
    save_detections(sd, store, detections, params)
    sd.save(xml)
    click.echo(f"saved interest points '{params.label}' + XML")


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("-l", "--label", "labels", multiple=True, default=("beads",),
              help="interest point label(s); repeat for multiple")
@click.option("--matchAcrossLabels", "match_across", is_flag=True,
              default=False,
              help="with multiple -l labels, also match between label classes")
@click.option("-m", "--method", default="FAST_ROTATION",
              type=click.Choice(["FAST_ROTATION", "FAST_TRANSLATION",
                                 "PRECISE_TRANSLATION", "ICP"]),
              help="matching method (SparkGeometricDescriptorMatching enum)")
@click.option("--transformationModel", "model", default="AFFINE",
              type=click.Choice(["TRANSLATION", "RIGID", "AFFINE"]))
@click.option("--regularizationModel", "reg", default="RIGID",
              type=click.Choice(["NONE", "IDENTITY", "TRANSLATION",
                                 "RIGID", "AFFINE"]))
@click.option("--lambda", "lam", default=0.1, type=float,
              help="regularization weight")
@click.option("-rtp", "--registrationTP", "registration_tp",
              default="TIMEPOINTS_INDIVIDUALLY",
              type=click.Choice(["TIMEPOINTS_INDIVIDUALLY", "ALL_TO_ALL",
                                 "ALL_TO_ALL_WITH_RANGE", "REFERENCE_TIMEPOINT"]))
@click.option("--referenceTP", "reference_tp", default=0, type=int)
@click.option("--rangeTP", "range_tp", default=5, type=int)
@click.option("-s", "--significance", "ratio_of_distance", default=3.0, type=float,
              help="descriptor ratio-of-distance threshold")
@click.option("-n", "--numNeighbors", "n_neighbors", default=3, type=int)
@click.option("-r", "--redundancy", "redundancy", default=1, type=int)
@click.option("-rit", "--ransacIterations", "ransaciterations", default=10000, type=int)
@click.option("-rme", "--ransacMaxError", "--ransacMaxEpsilon",
              "ransacmaxepsilon", default=5.0, type=float)
@click.option("-rmir", "--ransacMinInlierRatio", "ransacmininlierratio", default=0.1, type=float)
@click.option("-rmni", "--ransacMinNumInliers", "ransacminnuminliers", default=12, type=int)
@click.option("-rmc", "--ransacMultiConsensus", "ransac_multi", is_flag=True,
              default=False,
              help="ransac performs multiconsensus matching")
@click.option("-ime", "--icpMaxError", "--icpMaxDistance", "icpmaxdistance",
              default=2.5, type=float)
@click.option("-iit", "--icpIterations", "--icpMaxIterations",
              "icpmaxiterations", default=200, type=int)
@click.option("--icpUseRANSAC", "icp_use_ransac", is_flag=True, default=False,
              help="ICP filters correspondences with RANSAC every iteration")
@click.option("-sr", "--searchRadius", "search_radius", type=float,
              default=None,
              help="only for PRECISE_TRANSLATION: limit corresponding points "
                   "to this distance in global coordinates")
@click.option("-vr", "--viewReg", "view_reg", default="OVERLAPPING_ONLY",
              type=click.Choice(["OVERLAPPING_ONLY", "ALL_AGAINST_ALL"]),
              help="which view pairs to match")
@click.option("--interestPointsForOverlapOnly", "overlap_only_points",
              is_flag=True, help="match only points inside the pair overlap")
@click.option("-ipfr", "--interestpointsForReg", "ipfr", default=None,
              type=click.Choice(["ALL", "OVERLAPPING_ONLY"]),
              help="which interest points to use for pairwise registrations "
                   "(reference -ipfr; OVERLAPPING_ONLY is equivalent to "
                   "--interestPointsForOverlapOnly)")
@click.option("--clearCorrespondences", "clear_corrs", is_flag=True,
              help="drop existing correspondences instead of merging")
@click.option("--groupTiles", "group_tiles", is_flag=True,
              help="merge all tiles of one angle/channel/illum/timepoint")
@click.option("--groupChannels", "group_channels", is_flag=True,
              help="merge all channels of one angle/illum/tile/timepoint")
@click.option("--groupIllums", "group_illums", is_flag=True,
              help="merge all illuminations of one angle/channel/tile/timepoint")
@click.option("--splitTimepoints", "split_timepoints", is_flag=True,
              help="treat each timepoint as one grouped view")
@click.option("--interestPointMergeDistance", "merge_distance", default=5.0,
              type=float, help="merge radius (px) for grouped interest points")
def match_interestpoints_cmd(xml, dry_run, **kw):
    """Distributed pairwise interest-point matching
    (SparkGeometricDescriptorMatching)."""
    from ..io.interestpoints import InterestPointStore
    from ..models.matching import (
        MatchingParams,
        match_interest_points,
        save_matches,
    )

    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    labels = list(kw["labels"]) or ["beads"]
    params = MatchingParams(
        label=labels[0], labels=tuple(labels[1:]),
        match_across_labels=kw["match_across"],
        method=kw["method"], model=kw["model"],
        regularization=kw["reg"], lam=kw["lam"],
        n_neighbors=kw["n_neighbors"], redundancy=kw["redundancy"],
        ratio_of_distance=kw["ratio_of_distance"],
        ransac_iterations=kw["ransaciterations"],
        ransac_max_epsilon=kw["ransacmaxepsilon"],
        ransac_min_inlier_ratio=kw["ransacmininlierratio"],
        ransac_min_inliers=kw["ransacminnuminliers"],
        ransac_multi_consensus=kw["ransac_multi"],
        search_radius=kw["search_radius"],
        icp_max_distance=kw["icpmaxdistance"],
        icp_max_iterations=kw["icpmaxiterations"],
        icp_use_ransac=kw["icp_use_ransac"],
        overlap_filter=kw["view_reg"] == "OVERLAPPING_ONLY",
        registration_tp=kw["registration_tp"],
        reference_tp=kw["reference_tp"], range_tp=kw["range_tp"],
        interest_points_for_overlap_only=(kw["overlap_only_points"]
            or kw.get("ipfr") == "OVERLAPPING_ONLY"),
        clear_correspondences=kw["clear_corrs"],
        group_tiles=kw["group_tiles"], group_channels=kw["group_channels"],
        group_illums=kw["group_illums"],
        split_timepoints=kw["split_timepoints"],
        merge_distance=kw["merge_distance"],
    )
    store = InterestPointStore.for_project(sd)
    results = match_interest_points(sd, views, params, store)
    total = sum(len(r.ids_a) for r in results)
    click.echo(f"matched {total} correspondences over {len(results)} pairs")
    if dry_run:
        click.echo("dryRun: not saving")
        return
    save_matches(sd, store, results, params, views)
    click.echo("saved correspondences")

"""CLI: detect-interestpoints, match-interestpoints, clear-interestpoints
(reference tools SparkInterestPointDetection / SparkGeometricDescriptorMatching
/ ClearInterestPoints)."""

from __future__ import annotations

import click

from .common import (
    infrastructure_options,
    load_project,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("-l", "--label", default="beads", help="interest point label")
@click.option("-s", "--sigma", default=1.8, type=float,
              help="DoG sigma (at detection resolution)")
@click.option("-t", "--threshold", default=0.008, type=float,
              help="DoG response threshold")
@click.option("-dsxy", "--downsampleXY", "downsample_xy", default=2, type=int)
@click.option("-dsz", "--downsampleZ", "downsample_z", default=1, type=int)
@click.option("--minIntensity", "min_intensity", default=None, type=float)
@click.option("--maxIntensity", "max_intensity", default=None, type=float)
@click.option("--type", "extrema", default="MAX",
              type=click.Choice(["MAX", "MIN", "BOTH"]),
              help="detect maxima, minima or both")
@click.option("--overlappingOnly", "overlapping_only", is_flag=True,
              help="only detect in regions overlapping other selected views")
@click.option("--maxSpots", "max_spots", default=0, type=int,
              help="keep only the brightest N spots per view (0 = all)")
@click.option("--maxSpotsPerOverlap", "max_spots_per_overlap", is_flag=True,
              help="distribute --maxSpots over overlap regions by volume")
@click.option("--storeIntensities", "store_intensities", is_flag=True,
              help="sample + store per-point image intensities")
@click.option("--medianFilter", "median_radius", default=0, type=int,
              help="background-divide by per-slice median of this radius (0=off)")
@click.option("--blockSize", "block_size", default="512,512,128",
              help="detection block size at detection resolution")
def detect_interestpoints_cmd(xml, dry_run, **kw):
    """Distributed DoG interest-point detection (SparkInterestPointDetection)."""
    from ..io.dataset_io import ViewLoader
    from ..io.interestpoints import InterestPointStore
    from ..models.detection import (
        DetectionParams,
        detect_interest_points,
        save_detections,
    )
    from .common import parse_csv_ints

    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    params = DetectionParams(
        label=kw["label"], sigma=kw["sigma"], threshold=kw["threshold"],
        downsample_xy=kw["downsample_xy"], downsample_z=kw["downsample_z"],
        min_intensity=kw["min_intensity"], max_intensity=kw["max_intensity"],
        find_max=kw["extrema"] in ("MAX", "BOTH"),
        find_min=kw["extrema"] in ("MIN", "BOTH"),
        overlapping_only=kw["overlapping_only"],
        max_spots=kw["max_spots"],
        max_spots_per_overlap=kw["max_spots_per_overlap"],
        store_intensities=kw["store_intensities"],
        median_radius=kw["median_radius"],
        block_size=tuple(parse_csv_ints(kw["block_size"], 3)),
    )
    loader = ViewLoader(sd)
    detections = detect_interest_points(sd, loader, views, params)
    total = sum(len(d.points) for d in detections)
    print(f"detected {total} interest points over {len(detections)} views")
    if dry_run:
        print("dryRun: not saving")
        return
    store = InterestPointStore.for_project(sd)
    save_detections(sd, store, detections, params)
    sd.save(xml)
    print(f"saved interest points '{params.label}' + XML")

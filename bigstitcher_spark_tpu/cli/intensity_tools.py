"""CLI: match-intensities and solve-intensities (reference tools
SparkIntensityMatching.java / IntensitySolver.java)."""

from __future__ import annotations

import click

from .common import (
    infrastructure_options,
    load_project,
    parse_csv_ints,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("--coefficients", "coefficients", default="8,8,8",
              help="coefficient grid cells per view, e.g. 8,8,8")
@click.option("--renderScale", "render_scale", type=float, default=0.25,
              help="sampling scale inside overlaps")
@click.option("-m", "--method", type=click.Choice(["RANSAC", "HISTOGRAM"]),
              default="RANSAC")
@click.option("--ransacEpsilon", "ransac_epsilon", type=float, default=0.02)
@click.option("--ransacIterations", "ransac_iterations", type=int, default=1000)
@click.option("--minSamples", "min_samples", type=int, default=10)
@click.option("--intensityN5", "intensity_n5", default=None,
              help="output N5 (default: intensity.n5 next to the XML)")
def match_intensities_cmd(xml, dry_run, coefficients, render_scale, method,
                          ransac_epsilon, ransac_iterations, min_samples,
                          intensity_n5, **kw):
    """Pairwise per-cell intensity matching (SparkIntensityMatching)."""
    from ..io.dataset_io import ViewLoader
    from ..models.intensity import (
        IntensityParams,
        IntensityStore,
        match_intensities,
    )

    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    loader = ViewLoader(sd)
    params = IntensityParams(
        coefficients=tuple(parse_csv_ints(coefficients, 3)),
        render_scale=render_scale, method=method,
        ransac_epsilon=ransac_epsilon, ransac_iterations=ransac_iterations,
        min_samples_per_cell=min_samples,
    )
    matches = match_intensities(sd, loader, views, params)
    print(f"matched {len(matches)} coefficient-cell pairs")
    if dry_run:
        print("dryRun: not saving")
        return
    store = (IntensityStore(intensity_n5) if intensity_n5
             else IntensityStore.for_project(sd))
    store.save_matches(matches, params.coefficients)
    print(f"saved matches to {store.root}")


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("--lambda", "lam", type=float, default=0.1,
              help="regularization toward identity")
@click.option("--intensityN5", "intensity_n5", default=None,
              help="N5 with matches (default: intensity.n5 next to the XML)")
def solve_intensities_cmd(xml, dry_run, lam, intensity_n5, **kw):
    """Global solve of per-view intensity coefficient grids (IntensitySolver)."""
    from ..models.intensity import IntensityStore, solve_intensities

    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    store = (IntensityStore(intensity_n5) if intensity_n5
             else IntensityStore.for_project(sd))
    matches = store.load_all_matches()
    dims = store.coefficient_dims()
    if not matches or dims is None:
        raise click.ClickException(
            f"no intensity matches in {store.root}; run match-intensities first")
    coeffs = solve_intensities(matches, views, dims, lam)
    if dry_run:
        for v, c in sorted(coeffs.items()):
            print(f"  {v}: scale [{c[..., 0].min():.3f}, {c[..., 0].max():.3f}]"
                  f" offset [{c[..., 1].min():.1f}, {c[..., 1].max():.1f}]")
        print("dryRun: not saving")
        return
    for v, c in coeffs.items():
        store.save_coefficients(v, c)
    print(f"saved coefficients for {len(coeffs)} views to {store.root}")

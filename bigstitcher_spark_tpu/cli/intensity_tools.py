"""CLI: match-intensities and solve-intensities (reference tools
SparkIntensityMatching.java / IntensitySolver.java). Option spellings mirror
the reference exactly, with a few extra local aliases kept for
backwards-compatibility with earlier rounds of this repo."""

from __future__ import annotations

import click

from .common import (
    infrastructure_options,
    load_project,
    parse_csv_ints,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("--numCoefficients", "--coefficients", "coefficients",
              default="8,8,8",
              help="number of coefficients per dimension (default: 8,8,8)")
@click.option("--renderScale", "render_scale", type=float, default=0.25,
              help="at which scale to sample images (default: 0.25)")
@click.option("-m", "--method", type=click.Choice(["RANSAC", "HISTOGRAM"]),
              default="RANSAC")
@click.option("--maxEpsilon", "--ransacEpsilon", "ransac_epsilon", type=float,
              default=0.02,
              help="maximal allowed transfer error relative to the "
                   "[0,1]-normalized intensity range (default: 0.02 — the "
                   "reference's 5.1 of 255)")
@click.option("--numIterations", "--ransacIterations", "ransac_iterations",
              type=int, default=1000,
              help="number of RANSAC iterations (default: 1000)")
@click.option("--minSamples", "min_samples", type=int, default=10)
@click.option("--minThreshold", "min_threshold", type=float, default=1.0,
              help="discard intensities below this value (default: 1)")
@click.option("--maxThreshold", "max_threshold", type=float,
              default=float("nan"),
              help="discard intensities above this value (default: none)")
@click.option("--minNumCandidates", "min_num_candidates", type=int,
              default=1000,
              help="minimum overlapping samples per coefficient-cell pair "
                   "(default: 1000)")
@click.option("--minInlierRatio", "min_inlier_ratio", type=float, default=0.1,
              help="minimal inliers/candidates ratio (default: 0.1, RANSAC)")
@click.option("--minNumInliers", "min_num_inliers", type=int, default=10,
              help="minimally required inliers (default: 10, RANSAC)")
@click.option("--maxTrust", "max_trust", type=float, default=3.0,
              help="reject candidates with residual > maxTrust * median "
                   "(default: 3, RANSAC)")
@click.option("-o", "--outputPath", "--intensityN5", "intensity_n5",
              default=None,
              help="output N5 for pairwise matches (default: intensity.n5 "
                   "next to the XML)")
def match_intensities_cmd(xml, dry_run, coefficients, render_scale, method,
                          ransac_epsilon, ransac_iterations, min_samples,
                          min_threshold, max_threshold, min_num_candidates,
                          min_inlier_ratio, min_num_inliers, max_trust,
                          intensity_n5, **kw):
    """Pairwise per-cell intensity matching (SparkIntensityMatching)."""
    from ..io.dataset_io import ViewLoader
    from ..models.intensity import (
        IntensityParams,
        IntensityStore,
        match_intensities,
    )

    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    loader = ViewLoader(sd)
    params = IntensityParams(
        coefficients=tuple(parse_csv_ints(coefficients, 3)),
        render_scale=render_scale, method=method,
        ransac_epsilon=ransac_epsilon, ransac_iterations=ransac_iterations,
        min_samples_per_cell=min_samples,
        min_threshold=min_threshold, max_threshold=max_threshold,
        min_num_candidates=min_num_candidates,
        min_inlier_ratio=min_inlier_ratio, min_num_inliers=min_num_inliers,
        max_trust=max_trust,
    )
    matches = match_intensities(sd, loader, views, params)
    click.echo(f"matched {len(matches)} coefficient-cell pairs")
    if dry_run:
        click.echo("dryRun: not saving")
        return
    store = (IntensityStore(intensity_n5) if intensity_n5
             else IntensityStore.for_project(sd))
    store.save_matches(matches, params.coefficients)
    click.echo(f"saved matches to {store.root}")


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("--lambda", "lam", type=float, default=0.1,
              help="regularization toward identity")
@click.option("--numCoefficients", "num_coefficients", default=None,
              help="expected coefficients per dimension; validated against "
                   "the stored matches")
@click.option("--matchesPath", "matches_path", default=None,
              help="N5 with pairwise matches (default: the intensity N5)")
@click.option("--maxIterations", "max_iterations", type=int, default=2000,
              help="accepted for compatibility: this implementation solves "
                   "the global system exactly, no iteration limit applies")
@click.option("-o", "--intensityN5Path", "--intensityN5", "intensity_n5",
              default=None,
              help="N5 for matches/coefficients (default: intensity.n5 next "
                   "to the XML)")
@click.option("-s", "--intensityN5Storage", "intensity_storage", default=None,
              help="storage format of the intensity N5 (inferred from the "
                   "path; validated when given)")
@click.option("--intensityN5Group", "intensity_group", default=None,
              help="group inside the N5 holding coefficients (default: "
                   "coefficients)")
@click.option("--intensityN5Dataset", "intensity_dataset", default=None,
              help="dataset name for each view's coefficients (default: "
                   "coefficients)")
def solve_intensities_cmd(xml, dry_run, lam, num_coefficients, matches_path,
                          max_iterations, intensity_n5, intensity_storage,
                          intensity_group, intensity_dataset, **kw):
    """Global solve of per-view intensity coefficient grids (IntensitySolver)."""
    from ..models.intensity import IntensityStore, solve_intensities

    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    match_root = matches_path or intensity_n5
    store = (IntensityStore(match_root) if match_root
             else IntensityStore.for_project(sd))
    if intensity_storage and not store.store.format.name.lower().startswith(
            intensity_storage.lower().replace("ome-", "")):
        raise click.ClickException(
            f"--intensityN5Storage {intensity_storage} does not match the "
            f"store at {store.root} ({store.store.format.name})")
    matches = store.load_all_matches()
    dims = store.coefficient_dims()
    if not matches or dims is None:
        raise click.ClickException(
            f"no intensity matches in {store.root}; run match-intensities first")
    if num_coefficients is not None:
        from .common import parse_csv_ints as _pci

        want = tuple(_pci(num_coefficients, 3))
        if want != tuple(dims):
            raise click.ClickException(
                f"--numCoefficients {want} does not match the stored matches "
                f"({tuple(dims)})")
    coeffs = solve_intensities(matches, views, dims, lam)
    if dry_run:
        for v, c in sorted(coeffs.items()):
            click.echo(f"  {v}: scale [{c[..., 0].min():.3f}, {c[..., 0].max():.3f}]"
                  f" offset [{c[..., 1].min():.1f}, {c[..., 1].max():.1f}]")
        click.echo("dryRun: not saving")
        return
    out_store = (IntensityStore(intensity_n5)
                 if intensity_n5 and intensity_n5 != match_root else store)
    for v, c in coeffs.items():
        out_store.save_coefficients(v, c, group=intensity_group,
                                    dataset=intensity_dataset)
    click.echo(f"saved coefficients for {len(coeffs)} views to {out_store.root}")

"""``create-fusion-container`` and ``affine-fusion`` commands.

Reference tools: CreateFusionContainer.java (driver-only container setup) and
SparkAffineFusion.java (the distributed fusion workload). Flag names follow
the reference CLI surface.
"""

from __future__ import annotations

import os
import time

import click
import numpy as np

from ..io.chunkstore import StorageFormat
from ..io.container import (
    open_container,
    create_fusion_container,
    estimate_multires_pyramid,
    read_container_meta,
)
from ..io.dataset_io import ViewLoader
from ..io.spimdata import SpimData, ViewId
from ..models.affine_fusion import BlendParams, fuse_volume
from ..ops.fusion import FUSION_TYPES
from ..io.uris import has_scheme
from ..utils.geometry import Interval
from ..utils.viewselect import (
    anisotropy_factor_from_voxel_sizes,
    maximal_bounding_box,
)
from .common import (
    infrastructure_options,
    parse_csv_ints,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


def _abs_if_local(path: str) -> str:
    """abspath local paths; cloud URIs pass through untouched."""
    return path if has_scheme(path) else os.path.abspath(path)


_DTYPES = ("UINT8", "UINT16", "FLOAT32")


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("-o", "--outputPath", "--output", "output", required=True,
              help="output container path (.n5 / .zarr)")
@click.option("-s", "--storage", type=click.Choice(["N5", "ZARR", "HDF5"]),
              default="ZARR", help="storage format")
@click.option("-d", "--dataType", "data_type",
              type=click.Choice(_DTYPES), default="FLOAT32")
@click.option("--blockSize", "block_size", default="128,128,128",
              help="block size, e.g. 128,128,64")
@click.option("-ch", "--numChannels", "num_channels_opt", type=int,
              default=None,
              help="number of container channels (default: from the XML "
                   "view selection)")
@click.option("-tp", "--numTimepoints", "num_timepoints_opt", type=int,
              default=None,
              help="number of container timepoints (default: from the XML "
                   "view selection)")
@click.option("--bdv", is_flag=True, default=False,
              help="write a BDV-project layout (+XML) instead of a plain container")
@click.option("-xo", "--xmlout", "xml_out", default=None,
              help="output XML path for --bdv")
@click.option("--multiRes", "multi_res", is_flag=True, default=False,
              help="automatically create a multiresolution pyramid")
@click.option("-ds", "--downsampling", "downsampling", multiple=True,
              help="manual pyramid steps, e.g. -ds 1,1,1 -ds 2,2,1 -ds 4,4,2")
@click.option("--preserveAnisotropy", "preserve_anisotropy", is_flag=True,
              default=False)
@click.option("--anisotropyFactor", "anisotropy_factor", type=float,
              default=float("nan"))
@click.option("--minIntensity", "min_intensity", type=float, default=None)
@click.option("--maxIntensity", "max_intensity", type=float, default=None)
@click.option("-b", "--boundingBox", "bounding_box", default=None,
              help="use a named bounding box from the XML instead of the maximal one")
@click.option("-c", "--compression", default="zstd",
              type=click.Choice(["zstd", "gzip", "raw", "blosc", "bzip2", "xz",
                                 "lz4"]))
@click.option("-cl", "--compressionLevel", "compression_level", type=int,
              default=None,
              help="codec-specific compression level (CreateFusionContainer "
                   "-cl)")
def create_fusion_container_cmd(xml, output, storage, data_type, block_size,
                                num_channels_opt, num_timepoints_opt,
                                bdv, xml_out, multi_res, downsampling,
                                preserve_anisotropy, anisotropy_factor,
                                min_intensity, max_intensity, bounding_box,
                                compression, compression_level, dry_run,
                                **kwargs):
    """Create an empty fusion output container + metadata (driver-only)."""
    sd = SpimData.load(xml)
    views = select_views_from_kwargs(sd, kwargs)
    storage_format = StorageFormat(storage)
    if compression in ("xz", "lz4") and storage_format != StorageFormat.N5:
        raise click.ClickException(
            f"{compression} compression is only available for N5 containers")
    if compression_level is not None:
        compression = f"{compression}:{compression_level}"

    channels = sorted({sd.setups[v.setup].attributes.get("channel", 0) for v in views})
    tps = sorted({v.timepoint for v in views})
    num_channels = (num_channels_opt if num_channels_opt is not None
                    else len(channels))
    num_timepoints = (num_timepoints_opt if num_timepoints_opt is not None
                      else len(tps))

    if preserve_anisotropy and not np.isfinite(anisotropy_factor):
        anisotropy_factor = anisotropy_factor_from_voxel_sizes(sd, views)

    from ..models.affine_fusion import anisotropy_transform

    aniso = anisotropy_transform(anisotropy_factor) if preserve_anisotropy else None
    if bounding_box is not None:
        if bounding_box not in sd.bounding_boxes:
            raise click.ClickException(
                f"bounding box {bounding_box!r} not in XML; "
                f"have {sorted(sd.bounding_boxes)}"
            )
        bbox = sd.bounding_boxes[bounding_box]
        if aniso is not None:
            mn = list(bbox.min); mx = list(bbox.max)
            mn[2] = int(np.round(mn[2] / anisotropy_factor))
            mx[2] = int(np.round(mx[2] / anisotropy_factor))
            bbox = Interval(mn, mx)
    else:
        bbox = maximal_bounding_box(sd, views, aniso)

    bs = parse_csv_ints(block_size, 3)
    if downsampling:
        ds = [parse_csv_ints(d, 3) for d in downsampling]
    elif multi_res:
        ds = estimate_multires_pyramid(bbox.shape, anisotropy_factor
                                       if preserve_anisotropy else float("nan"))
    else:
        ds = [[1, 1, 1]]

    click.echo(f"BoundingBox: {bbox.min} -> {bbox.max} dims={bbox.shape}")
    click.echo(f"numChannels={num_channels} numTimepoints={num_timepoints}")
    click.echo(f"pyramid: {ds}")
    if dry_run:
        click.echo("(dry run, not writing)")
        return

    bdv_xml = xml_out or output + ".xml"
    setup_offset = 0
    append_sd = None
    if bdv and os.path.exists(bdv_xml):
        # fuse into the EXISTING BDV project: new ViewSetups get the next
        # setup/channel ids (BDVSparkInstantiateViewSetup.java:57-112)
        if storage_format != StorageFormat.N5:
            raise click.ClickException(
                "appending to an existing BDV project XML is supported for "
                "N5 containers (delete the XML for a fresh project)")
        append_sd = SpimData.load(bdv_xml)
        existing_root = append_sd.resolve_loader_path()

        def canon(p):
            from ..io import uris

            return (uris.normpath(p) if has_scheme(p)
                    else os.path.realpath(p))

        if canon(existing_root) != canon(output):
            raise click.ClickException(
                f"existing BDV project {bdv_xml} points at container "
                f"{existing_root!r}, not the requested output {output!r} — "
                "refusing to append (pick the project's own container, or a "
                "fresh --xmlout)")
        setup_offset = max(append_sd.setups) + 1 if append_sd.setups else 0
        click.echo(f"appending to existing BDV project {bdv_xml}: "
                   f"new setups start at {setup_offset}")

    meta = create_fusion_container(
        output, storage_format, _abs_if_local(xml),
        num_timepoints, num_channels, bbox,
        data_type=data_type.lower(), block_size=bs, downsamplings=ds,
        compression=compression, bdv=bdv,
        preserve_anisotropy=preserve_anisotropy,
        anisotropy_factor=anisotropy_factor,
        min_intensity=min_intensity, max_intensity=max_intensity,
        setup_id_offset=setup_offset,
    )
    if bdv and append_sd is not None:
        _append_bdv_output_xml(append_sd, bdv_xml, meta, setup_offset)
    elif bdv:
        _write_bdv_output_xml(bdv_xml, output, meta, storage_format)
    click.echo(f"created {meta.fusion_format} container at {output}")


def _append_bdv_output_xml(sd, xml_out: str, meta, setup_offset: int) -> None:
    """Append this fusion's ViewSetups to an existing BDV project: next
    channel ids, identity registrations, shared container
    (BDVSparkInstantiateViewSetup.java:57-112 — the default rule increments
    the channel when nothing else distinguishes the new setups)."""
    from ..io.spimdata import AttributeEntity, ViewSetup, ViewTransform
    from ..utils.geometry import identity_affine

    next_channel = max(sd.attributes["channel"], default=-1) + 1
    dims = meta.bbox.shape
    for c in range(meta.num_channels):
        ch = next_channel + c
        sid = setup_offset + c
        sd.attributes["channel"][ch] = AttributeEntity(ch, f"Channel {ch}")
        sd.setups[sid] = ViewSetup(
            id=sid, name=f"setup {sid}", size=tuple(dims),
            attributes={"illumination": 0, "channel": ch, "tile": 0,
                        "angle": 0},
        )
        for t in range(meta.num_timepoints):
            if t not in sd.timepoints:
                sd.timepoints.append(t)
            sd.registrations[ViewId(t, sid)] = [
                ViewTransform("fused", identity_affine())
            ]
    sd.timepoints.sort()
    sd.save(xml_out)


def _write_bdv_output_xml(xml_out: str, container: str, meta, storage_format) -> None:
    """Minimal BDV project XML for the fused dataset
    (SpimData2Tools.createNewSpimDataForFusion role)."""
    from ..io.spimdata import (
        AttributeEntity, ImageLoader, SpimData, ViewSetup, ViewTransform,
    )
    from ..utils.geometry import identity_affine

    out = SpimData()
    fmt = {StorageFormat.N5: "bdv.n5", StorageFormat.ZARR: "bdv.zarr",
           StorageFormat.HDF5: "bdv.hdf5"}[storage_format]
    out.image_loader = ImageLoader(format=fmt, path=_abs_if_local(container),
                                  path_type="absolute")
    out.timepoints = list(range(meta.num_timepoints))
    dims = meta.bbox.shape
    out.attributes["illumination"][0] = AttributeEntity(0, "0")
    out.attributes["angle"][0] = AttributeEntity(0, "0")
    out.attributes["tile"][0] = AttributeEntity(0, "0")
    for c in range(meta.num_channels):
        out.attributes["channel"][c] = AttributeEntity(c, f"Channel {c}")
        out.setups[c] = ViewSetup(
            id=c, name=f"setup {c}", size=tuple(dims),
            attributes={"illumination": 0, "channel": c, "tile": 0, "angle": 0},
        )
        for t in range(meta.num_timepoints):
            out.registrations[ViewId(t, c)] = [
                ViewTransform("fused", identity_affine())
            ]
    out.save(xml_out)


@click.command()
@infrastructure_options
@click.option("-o", "--n5Path", "--output", "output", required=True,
              help="fusion container created by create-fusion-container")
@click.option("-s", "--storage", "storage_opt", default=None,
              type=click.Choice(["N5", "ZARR", "HDF5"]),
              help="container storage format (validated against the "
                   "container's own metadata)")
@view_selection_options
@click.option("-f", "--fusion", "--fusionType", "fusion_type",
              type=click.Choice(FUSION_TYPES, case_sensitive=False),
              default="AVG_BLEND")
@click.option("--blockScale", "block_scale", default="2,2,1",
              help="how many container blocks per compute block")
@click.option("--masks", is_flag=True, default=False,
              help="write coverage masks instead of fused data")
@click.option("--maskOffset", "mask_offset", default="0.0,0.0,0.0")
@click.option("--blendingRange", "blending_range", default="40,40,40")
@click.option("--blendingBorder", "blending_border", default="0,0,0")
@click.option("-c", "--channelIndex", "channel_index", type=int, default=None,
              help="process only this channel index of the container")
@click.option("-t", "--timepointIndex", "timepoint_index", type=int,
              default=None,
              help="process only this timepoint index of the container")
@click.option("--prefetch/--no-prefetch", "prefetch", default=True,
              help="prefetch source chunks ahead of the kernel (always on in "
                   "this implementation's host IO pipeline; --no-prefetch "
                   "serializes IO for debugging)")
@click.option("--intensityN5", "intensity_n5", default=None, is_flag=False,
              flag_value="",
              help="apply solved intensity coefficients (optionally give the "
                   "N5 path; default: intensity.n5 next to the input XML)")
@click.option("--devices", "devices", type=int, default=None,
              help="local devices to shard the block grid over (default: "
                   "all; 1 selects the single-device composite/per-block "
                   "paths — the control runs --trace attribution compares "
                   "against)")
@click.option("--pyramid/--no-pyramid", "pyramid_epilogue", default=False,
              help="materialize the container's downsample pyramid as a "
                   "fused kernel epilogue while the data is device-"
                   "resident, shipped in the same drain (bit-identical to "
                   "the downsample stage, which then skips those levels "
                   "instead of re-reading the full-res container)")
def affine_fusion_cmd(output, storage_opt, fusion_type, block_scale, masks,
                      mask_offset, blending_range, blending_border,
                      channel_index, timepoint_index, prefetch, intensity_n5,
                      devices, pyramid_epilogue, dry_run, **kwargs):
    """Fuse all views into the prepared container (THE workload)."""
    t_start = time.time()
    store = open_container(output)
    if storage_opt is not None and store.format != StorageFormat(storage_opt):
        raise click.ClickException(
            f"--storage {storage_opt} does not match the container at "
            f"{output} ({store.format.name})")
    try:
        meta = read_container_meta(store)
    except ValueError as e:
        raise click.ClickException(str(e)) from e
    click.echo(f"FusionFormat: {meta.fusion_format}; bbox {meta.bbox.min}->"
               f"{meta.bbox.max}; dataType {meta.data_type}")
    sd = SpimData.load(meta.input_xml)
    loader = ViewLoader(sd)
    all_views = select_views_from_kwargs(sd, kwargs)

    coefficients = None
    if intensity_n5 is not None:
        from ..models.intensity import IntensityStore

        istore = (IntensityStore(intensity_n5) if intensity_n5
                  else IntensityStore.for_project(sd))
        coefficients = {}
        for v in all_views:
            c = istore.load_coefficients(v)
            if c is not None:
                coefficients[v] = c.astype(np.float32)
        click.echo(f"intensity correction: coefficients for "
                   f"{len(coefficients)}/{len(all_views)} views from {istore.root}")

    blend = BlendParams(
        border=tuple(float(v) for v in blending_border.split(",")),
        range=tuple(float(v) for v in blending_range.split(",")),
    )
    bscale = parse_csv_ints(block_scale, 3)
    is_zarr5d = meta.fusion_format in ("OME-ZARR", "BDV/OME-ZARR")

    # container channel/timepoint indices are positions in the FULL sorted
    # lists — keep them stable under --channelIndex/--timepointIndex filtering
    # so data lands in the matching mr_infos dataset / zarr slot
    channels = sorted({sd.setups[v.setup].attributes.get("channel", 0)
                       for v in all_views})
    tps = sorted({v.timepoint for v in all_views})
    c_indices = ([channel_index] if channel_index is not None
                 else list(range(len(channels))))
    t_indices = ([timepoint_index] if timepoint_index is not None
                 else list(range(len(tps))))
    moff = tuple(float(v) for v in mask_offset.split(","))

    total_vox = 0
    for ti in t_indices:
        t = tps[ti]
        for ci in c_indices:
            c = channels[ci]
            views = [
                v for v in all_views
                if v.timepoint == t
                and sd.setups[v.setup].attributes.get("channel", 0) == c
            ]
            if not views:
                continue
            mr = meta.mr_infos[ci + ti * meta.num_channels]
            ds = store.open_dataset(mr[0].dataset.strip("/"))
            click.echo(f"fusing channel {c} timepoint {t}: {len(views)} views "
                       f"-> {mr[0].dataset}")
            if dry_run:
                continue
            pyr = None
            if pyramid_epilogue and len(mr) > 1:
                from ..models.affine_fusion import pyramid_from_mr

                pyr = pyramid_from_mr(store, mr)
            stats = fuse_volume(
                sd, loader, views, ds, meta.bbox,
                block_size=tuple(meta.block_size), block_scale=tuple(bscale),
                fusion_type=fusion_type.upper(), blend=blend,
                anisotropy_factor=(meta.anisotropy_factor
                                   if meta.preserve_anisotropy else float("nan")),
                out_dtype=meta.data_type,
                min_intensity=meta.min_intensity,
                max_intensity=meta.max_intensity,
                masks=masks,
                mask_offset=moff,
                zarr_ct=(ci, ti) if is_zarr5d else None,
                coefficients=coefficients,
                devices=devices,
                io_threads=4 if prefetch else 1,
                pyramid=pyr,
            )
            total_vox += stats.voxels
            click.echo(f"  {stats.voxels} voxels in {stats.seconds:.2f}s "
                       f"({stats.voxels / max(stats.seconds, 1e-9):,.0f} vox/s; "
                       f"{stats.skipped_empty} empty blocks skipped)")
            if stats.pyramid_levels:
                click.echo(
                    f"  epilogue: {stats.pyramid_levels} pyramid level(s), "
                    f"{stats.pyramid_voxels} voxels shipped in the fusion "
                    "drain ("
                    f"{(stats.voxels + stats.pyramid_voxels) / max(stats.seconds, 1e-9):,.0f}"
                    " vox/s incl. pyramid)")
            if len(mr) > 1 and not dry_run:
                _write_pyramid(store, mr, is_zarr5d, (ci, ti),
                               epilogue_levels=stats.pyramid_levels)
    click.echo(f"done, {total_vox} voxels, took {time.time() - t_start:.1f}s")


def _write_pyramid(store, mr_levels, is_zarr5d, ct, epilogue_levels=0):
    """Downsample s0 into the remaining pyramid levels
    (SparkAffineFusion.java:703-782). Each level reads chunks the previous
    stage may have written on another host -> barrier per boundary.

    ``epilogue_levels``: how many leading levels the fusion drivers already
    materialized as a fused multiscale epilogue this run. Their container
    markers are set (and stale ones from earlier runs revoked) before the
    barrier, then ``downsample_pyramid_level(skip_existing=True)`` skips
    exactly those — no full-res container re-read for levels that rode the
    fusion drain."""
    from ..io.container import set_epilogue_written
    from ..models.downsample_driver import downsample_pyramid_level
    from ..parallel.distributed import barrier, world

    if world()[0] == 0:  # one writer for the shared container attributes
        for lvl in range(1, len(mr_levels)):
            set_epilogue_written(store, mr_levels[lvl].dataset, ct,
                                 lvl <= epilogue_levels)
    barrier("fusion-s0")
    for lvl in range(1, len(mr_levels)):
        downsample_pyramid_level(store, mr_levels[lvl - 1], mr_levels[lvl],
                                 is_zarr5d, ct, skip_existing=True)
        barrier(f"fusion-s{lvl}")


@click.command()
@infrastructure_options
@click.option("-o", "--n5Path", "--output", "output", required=True,
              help="fusion container created by create-fusion-container, or "
                   "a fresh path with -x/--dataType (direct-output mode)")
@click.option("-x", "--xml", "xml", default=None,
              help="dataset XML (direct-output mode only; containers carry "
                   "their InputXML)")
@view_selection_options
@click.option("-ip", "--interestPoints", "-l", "--label", "labels",
              multiple=True, default=("beads",),
              help="interest point label(s) defining the deformation")
@click.option("-cpd", "--controlPointDistance", "cpd", type=float, default=10.0,
              help="control point grid spacing in px")
@click.option("--alpha", type=float, default=1.0,
              help="inverse-distance weight exponent")
@click.option("--fusionType", "fusion_type",
              type=click.Choice(FUSION_TYPES, case_sensitive=False),
              default="AVG_BLEND")
@click.option("--blockScale", "block_scale", default="2,2,1")
@click.option("--blendingRange", "blending_range", default="40,40,40")
@click.option("--blendingBorder", "blending_border", default="0,0,0")
@click.option("--channelIndex", "channel_index", type=int, default=None)
@click.option("--timepointIndex", "timepoint_index", type=int, default=None)
@click.option("-s", "--storage", "storage_opt", default=None,
              type=click.Choice(["N5", "ZARR", "HDF5"]),
              help="storage format for direct-output mode (default ZARR)")
@click.option("-d", "--n5Dataset", "n5_dataset", default=None,
              help="accepted for compatibility; the container layout fixes "
                   "the dataset names")
@click.option("-p", "--dataType", "data_type", default=None,
              type=click.Choice(_DTYPES),
              help="output data type (direct-output mode)")
@click.option("--minIntensity", "min_intensity", type=float, default=None)
@click.option("--maxIntensity", "max_intensity", type=float, default=None)
@click.option("-b", "--boundingBox", "bounding_box", default=None,
              help="named bounding box (direct-output mode)")
@click.option("--bdv", is_flag=True, default=False,
              help="also write a BDV project XML (direct-output mode)")
@click.option("-xo", "--xmlout", "xml_out", default=None,
              help="output XML path for --bdv (direct-output mode)")
def nonrigid_fusion_cmd(output, xml, labels, cpd, alpha, fusion_type,
                        block_scale, blending_range, blending_border,
                        channel_index, timepoint_index, storage_opt,
                        n5_dataset, data_type, min_intensity, max_intensity,
                        bounding_box, bdv, xml_out, dry_run, **kwargs):
    """Distributed non-rigid fusion driven by corresponding interest points
    (SparkNonRigidFusion)."""
    from ..io.interestpoints import InterestPointStore
    from ..models.nonrigid_fusion import (
        build_unique_points,
        fuse_nonrigid_volume,
    )

    t_start = time.time()
    try:
        store = open_container(output)
        meta = read_container_meta(store)
    except (ValueError, FileNotFoundError) as e:
        # direct-output mode (the reference's SparkNonRigidFusion writes
        # straight to an N5/ZARR, no create-fusion-container step): create
        # the container here from -x/--dataType/--boundingBox
        if xml is None or data_type is None:
            raise click.ClickException(
                f"{output} is not a fusion container ({e}); for direct "
                "output pass -x <dataset.xml> and -p/--dataType "
                "(plus optionally -s, -b, --minIntensity/--maxIntensity, "
                "--bdv/-xo)") from e
        # call the container-creation logic as a plain function (the
        # undecorated click callback) so stdout streams normally and the
        # view-selection/infrastructure flags given to nonrigid-fusion
        # carry through to the container bounding box (ADVICE r4)
        create_fusion_container_cmd.callback(
            xml=xml, output=output, storage=storage_opt or "ZARR",
            data_type=data_type, block_size="128,128,128",
            num_channels_opt=None, num_timepoints_opt=None,
            bdv=bdv, xml_out=xml_out, multi_res=False, downsampling=(),
            preserve_anisotropy=False, anisotropy_factor=float("nan"),
            min_intensity=min_intensity, max_intensity=max_intensity,
            bounding_box=bounding_box, compression="zstd",
            compression_level=None, dry_run=False, **kwargs,
        )
        click.echo(f"direct output: created container at {output}")
        store = open_container(output)
        meta = read_container_meta(store)
    sd = SpimData.load(meta.input_xml)
    loader = ViewLoader(sd)
    all_views = select_views_from_kwargs(sd, kwargs)
    ip_store = InterestPointStore.for_project(sd)

    blend = BlendParams(
        border=tuple(float(v) for v in blending_border.split(",")),
        range=tuple(float(v) for v in blending_range.split(",")),
    )
    bscale = parse_csv_ints(block_scale, 3)
    is_zarr5d = meta.fusion_format in ("OME-ZARR", "BDV/OME-ZARR")
    channels = sorted({sd.setups[v.setup].attributes.get("channel", 0)
                       for v in all_views})
    tps = sorted({v.timepoint for v in all_views})
    c_indices = ([channel_index] if channel_index is not None
                 else list(range(len(channels))))
    t_indices = ([timepoint_index] if timepoint_index is not None
                 else list(range(len(tps))))

    total_vox = 0
    for ti in t_indices:
        t = tps[ti]
        for ci in c_indices:
            c = channels[ci]
            views = [
                v for v in all_views
                if v.timepoint == t
                and sd.setups[v.setup].attributes.get("channel", 0) == c
            ]
            if not views:
                continue
            # deformation may use IPs of ALL views of this timepoint
            # (corresponding views need not be restricted to the channel)
            ip_views = [v for v in all_views if v.timepoint == t]
            unique = build_unique_points(sd, ip_store, ip_views, list(labels))
            mr = meta.mr_infos[ci + ti * meta.num_channels]
            ds = store.open_dataset(mr[0].dataset.strip("/"))
            click.echo(f"nonrigid fusing channel {c} timepoint {t}: "
                       f"{len(views)} views -> {mr[0].dataset}")
            if dry_run:
                continue
            stats = fuse_nonrigid_volume(
                sd, loader, views, unique, ds, meta.bbox,
                block_size=tuple(meta.block_size), block_scale=tuple(bscale),
                cpd=cpd, alpha=alpha,
                fusion_type=fusion_type.upper(), blend=blend,
                anisotropy_factor=(meta.anisotropy_factor
                                   if meta.preserve_anisotropy else float("nan")),
                out_dtype=meta.data_type,
                min_intensity=meta.min_intensity,
                max_intensity=meta.max_intensity,
                zarr_ct=(ci, ti) if is_zarr5d else None,
            )
            total_vox += stats.voxels
            click.echo(f"  {stats.voxels} voxels in {stats.seconds:.2f}s")
            if len(mr) > 1 and not dry_run:
                _write_pyramid(store, mr, is_zarr5d, (ci, ti))
    click.echo(f"done, {total_vox} voxels, took {time.time() - t_start:.1f}s")

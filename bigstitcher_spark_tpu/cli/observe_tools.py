"""Live-observability CLI: ``bst top`` / ``trace-dump`` / ``history`` /
``perf-diff``.

``bst top`` is the operator's live terminal view of a resident daemon —
queue depth, per-share runtime, per-job progress/ETA and stall state,
cache hit ratios and the in-flight byte high-water — polled over the
daemon socket (or its HTTP ``/status`` endpoint with ``--url``).
``bst trace-dump`` snapshots the daemon's always-on flight-recorder ring
to a Perfetto JSON on demand, without pausing jobs. ``bst history`` and
``bst perf-diff`` browse and compare the cross-run manifest records the
``BST_HISTORY_DIR`` store accumulates (observe/history.py) — the
substrate ``bst tune`` will replay.
"""

from __future__ import annotations

import json as _json
import sys
import time

import click


def _socket_opt(f):
    return click.option("--socket", "socket_path", default=None,
                        help="daemon Unix socket (default: "
                             "BST_SERVE_SOCKET or the per-user temp-dir "
                             "path)")(f)


def _history_dir_opt(f):
    return click.option("--history-dir", "history_dir", default=None,
                        help="history store directory (default: "
                             "BST_HISTORY_DIR)")(f)


def _fmt_bytes(n) -> str:
    """telemetry_tools' formatter, tolerant of missing values (a daemon
    answering mid-warmup may not have every gauge yet)."""
    from .telemetry_tools import _fmt_bytes as _fmt

    try:
        return _fmt(float(n))
    except (TypeError, ValueError):
        return "?"


def _hit_ratio(stats: dict) -> str:
    h, m = stats.get("hits", 0) or 0, stats.get("misses", 0) or 0
    return f"{h / (h + m) * 100:.1f}%" if h + m else "-"


def _pair_util(util: dict) -> str:
    """Device utilization of this rank's most recent pair-scheduler stage
    ({stage: {util_pct, ...}} from the relay snapshot)."""
    pcts = [v.get("util_pct") for v in util.values()
            if isinstance(v, dict) and v.get("util_pct") is not None]
    return f"{min(pcts):.0f}%" if pcts else "-"


def _fetch(socket_path, url):
    """One (status, jobs) sample, over HTTP when --url, else the socket."""
    if url:
        import urllib.request

        base = url.rstrip("/")
        with urllib.request.urlopen(base + "/status", timeout=5) as r:
            status = _json.load(r)
        with urllib.request.urlopen(base + "/jobs", timeout=5) as r:
            jobs = _json.load(r).get("jobs", [])
        return status, jobs
    from ..serve import client

    resp = client.list_jobs(socket_path)
    return resp["daemon"], resp["jobs"]


def _render_top(status: dict, jobs: list[dict]) -> str:
    proc = status.get("process", {})
    cc = status.get("chunk_cache", {})
    cf = status.get("compiled_fn", {})
    infl = status.get("inflight", {})
    dag = status.get("dag", {})
    lines = [
        f"bst serve pid {status.get('pid')}  up {status.get('uptime_s')}s"
        f"  slots {status.get('slots')}  queued {status.get('queue_depth')}"
        f"  active {status.get('active')}"
        f"  stalled {len(status.get('stalled') or [])}",
        f"process: rss {_fmt_bytes(proc.get('rss_bytes'))}  "
        f"threads {proc.get('threads', '?')}  "
        f"fds {proc.get('open_fds', '?')}",
        f"caches: chunk {_hit_ratio(cc)} hit "
        f"({cc.get('entries', 0)} entries, {_fmt_bytes(cc.get('bytes', 0))})"
        f"  disk-tier {_fmt_bytes((cc.get('disk') or {}).get('bytes', 0))}"
        f" ({_fmt_bytes((cc.get('disk') or {}).get('hit_bytes', 0))}"
        f" served)  prefetch {_hit_ratio(cc.get('prefetch') or {})} hit"
        f"  compiled-fn warm {cf.get('warm_hits', 0)}"
        f" / cold {cf.get('cold_builds', 0)}",
        f"inflight: {_fmt_bytes(infl.get('bytes', 0))} now, "
        f"{_fmt_bytes(infl.get('highwater_bytes', 0))} high-water"
        f"  |  dag exchange {_fmt_bytes(dag.get('exchange_bytes', 0))}"
        f" / {dag.get('exchange_blocks', 0)} blk, "
        f"stall {round(dag.get('producer_stall_s', 0) or 0, 1)}s "
        f"wait {round(dag.get('consumer_wait_s', 0) or 0, 1)}s",
    ]
    shares = status.get("share_runtime_s") or {}
    if shares:
        lines.append("shares: " + "  ".join(
            f"{k}={v}s" for k, v in sorted(shares.items())))
    lines.append("")
    lines.append(f"{'JOB':>6}  {'STATE':<9} {'TOOL':<22} "
                 f"{'PROGRESS':<22} {'ETA':>6} {'WAIT':>7} {'RUN':>8}")
    for j in jobs:
        p = j.get("progress") or {}
        prog = (f"{p.get('done')}/{p.get('total')} "
                f"({p.get('rate_per_s')}/s)" if p else "-")
        eta = f"{p.get('eta_s')}s" if p.get("eta_s") is not None else "-"
        run = f"{j['seconds']}s" if "seconds" in j else "-"
        state = j["state"] + ("!" if j.get("stalled") else "")
        line = (f"{j['id']:>6}  {state:<9} {j['tool']:<22} "
                f"{prog:<22} {eta:>6} {j['wait_s']:>6}s {run:>8}")
        if j.get("stalled"):
            line += f"  STALLED {j.get('stalled_for_s', '?')}s"
        if j.get("waiting_on"):
            line += f"  after {','.join(j['waiting_on'])}"
        lines.append(line)
    return "\n".join(lines)


def _fetch_cluster(socket_path, url) -> dict:
    """The relay collector's per-rank document, over HTTP (/cluster)
    when --url, else the daemon socket's `cluster` op."""
    if url:
        import urllib.request

        with urllib.request.urlopen(url.rstrip("/") + "/cluster",
                                    timeout=5) as r:
            return _json.load(r)
    from ..serve import client

    return client.cluster_status(socket_path)


def _render_cluster(doc: dict) -> str:
    col = doc.get("collector") or {}
    lines = [
        f"relay collector {col.get('address')}  up {col.get('uptime_s')}s"
        f"  ranks {col.get('ranks')} ({col.get('connected')} connected)"
        f"  stall timeout {col.get('stall_timeout_s')}s",
        "",
        f"{'HOST':<18} {'RANK':>4}  {'STATE':<9} {'AGE':>6} "
        f"{'PROGRESS':<24} {'CACHE':>6} {'DISK':>7} {'PF':>6} "
        f"{'PAIR':>6} {'INFLIGHT-HW':>11} {'DROP':>5}",
    ]
    for r in doc.get("ranks", []):
        p = r.get("progress") or {}
        prog = (f"{p.get('stage', '?')} {p.get('done')}/{p.get('total')}"
                if p else "-")
        if p.get("finished"):
            prog += " done"
        state = ("done" if r.get("done")
                 else "STALLED" if r.get("stalled")
                 else "live" if r.get("connected") else "lost")
        infl = (r.get("inflight") or {}).get("highwater_bytes")
        drop = r.get("dropped") or {}
        dropn = (drop.get("queue", 0) or 0) + (drop.get("conn", 0) or 0)
        cc = r.get("chunk_cache") or {}
        lines.append(
            f"{r.get('host', '?'):<18} {r.get('process_index', '?'):>4}  "
            f"{state:<9} {r.get('age_s', '?'):>5}s {prog:<24} "
            f"{_hit_ratio(cc):>6} "
            f"{_fmt_bytes((cc.get('disk') or {}).get('bytes', 0)):>7} "
            f"{_hit_ratio(cc.get('prefetch') or {}):>6} "
            f"{_pair_util(r.get('pair_util') or {}):>6} "
            f"{_fmt_bytes(infl):>11} {dropn:>5}")
    if not doc.get("ranks"):
        lines.append("(no ranks connected yet — workers push when "
                     "BST_TELEMETRY_RELAY points here)")
    return "\n".join(lines)


@click.command()
@_socket_opt
@click.option("--url", "url", default=None,
              help="poll the daemon's HTTP exporter (/status, /jobs) "
                   "instead of the socket, e.g. http://127.0.0.1:9100")
@click.option("--cluster", "cluster", is_flag=True, default=False,
              help="show the relay collector's per-host rank rows "
                   "(/cluster) instead of the local job table")
@click.option("--interval", type=float, default=2.0, show_default=True,
              help="refresh period in seconds")
@click.option("--once", is_flag=True, default=False,
              help="render a single frame and exit (scripts, tests)")
def top_cmd(socket_path, url, cluster, interval, once):
    """Live terminal view of a `bst serve` daemon.

    Shows queue depth and per-share runtime, each job's progress/ETA and
    stall state, cache hit ratios, and the in-flight byte high-water —
    refreshed every --interval seconds until Ctrl-C. With --cluster,
    shows the pod view instead: one row per relayed rank (host, heartbeat
    age, stage progress, stall verdict, cache ratio, pair-scheduler
    device utilization, in-flight high-water, relay drops)."""
    def frame() -> str:
        if cluster:
            return _render_cluster(_fetch_cluster(socket_path, url))
        return _render_top(*_fetch(socket_path, url))

    try:
        rendered = frame()
    except (OSError, RuntimeError, ValueError) as e:
        raise click.ClickException(
            f"{e} — is a daemon running? start one with `bst serve`")
    if once:
        click.echo(rendered)
        return
    try:
        while True:
            click.echo("\x1b[2J\x1b[H", nl=False)   # clear + home
            click.echo(rendered)
            click.echo(f"\n[{time.strftime('%H:%M:%S')}] refresh every "
                       f"{interval}s — Ctrl-C to exit")
            time.sleep(max(0.2, interval))
            rendered = frame()
    except KeyboardInterrupt:
        pass
    except (OSError, RuntimeError, ValueError) as e:
        raise click.ClickException(f"daemon went away: {e}")


@click.command()
@_socket_opt
@click.option("--out", "out", default=None,
              help="output path for the Perfetto JSON (default: "
                   "trace-dump-<n>.json in the daemon's jobs root)")
@click.option("--cluster", "cluster", is_flag=True, default=False,
              help="pull every relay-connected rank's live ring too and "
                   "fold them (barrier-aligned) into the one file")
def trace_dump_cmd(socket_path, out, cluster):
    """Snapshot the daemon's live flight-recorder ring to Perfetto JSON.

    The daemon records its timeline always (bounded ring, newest events
    win); this dumps the current contents WITHOUT pausing jobs or
    stopping the recorder — load the file in ui.perfetto.dev or run
    `bst trace-report` on it. With --cluster, the daemon's relay
    collector requests a live ring snapshot from every connected rank
    over the relay and merges them with its own onto one clock-aligned
    timeline — the whole pod, mid-run."""
    import os

    from ..serve import client

    try:
        resp = client.trace_dump(socket_path,
                                 out=os.path.abspath(out) if out else None,
                                 cluster=cluster)
    except (OSError, RuntimeError) as e:
        raise click.ClickException(
            f"{e} — is a daemon running? start one with `bst serve`")
    if cluster:
        line = (f"{resp.get('path')} ({resp.get('ranks')}/"
                f"{resp.get('asked')} rank ring(s)"
                + (", local ring" if resp.get("local_ring") else "")
                + f"; analyze with 'bst trace-report')")
        if resp.get("missing"):
            line += f"  WARNING: {resp['missing']} rank(s) did not answer"
        click.echo(line)
        return
    click.echo(f"{resp.get('path')} ({resp.get('buffered')} events "
               f"buffered, {resp.get('dropped')} dropped; analyze with "
               f"'bst trace-report')")


@click.group(invoke_without_command=True)
@click.pass_context
def history_cmd(ctx):
    """Browse the cross-run manifest history store (BST_HISTORY_DIR)."""
    if ctx.invoked_subcommand is None:
        ctx.invoke(history_list_cmd)


@history_cmd.command("list")
@_history_dir_opt
@click.option("--tool", default=None,
              help="only records produced by this tool")
@click.option("--since", default=None, metavar="STAMP",
              help="only records at/after this ISO stamp (prefixes "
                   "work: 2026-08, 2026-08-06T12)")
@click.option("--limit", type=int, default=None,
              help="keep only the newest N records (after filters)")
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable index entries (stable keys: id, "
                   "ts, tool, job, status, seconds, file)")
def history_list_cmd(history_dir, tool, since, limit, as_json):
    """List recorded runs/jobs, oldest first."""
    from ..observe import history

    try:
        entries = history.list_records(history_dir, tool=tool,
                                       since=since, limit=limit)
    except FileNotFoundError as e:
        raise click.ClickException(str(e))
    if as_json:
        click.echo(_json.dumps(entries, indent=1, default=str))
        return
    if not entries:
        click.echo("history is empty (runs record when BST_HISTORY_DIR "
                   "is set; import manifests with `bst history add`)")
        return
    for e in entries:
        line = (f"{e.get('ts', '?'):<20} {e.get('status', '?'):<9} "
                f"{e.get('seconds', '?'):>9}s  {e['id']}")
        if e.get("job"):
            line += f"  (job {e['job']})"
        click.echo(line)


@history_cmd.command("show")
@_history_dir_opt
@click.argument("record_id")
def history_show_cmd(history_dir, record_id):
    """Print one record (by id, unique prefix, or -1 for the latest)."""
    from ..observe import history

    try:
        rec = history.load_record(record_id, history_dir)
    except (FileNotFoundError, KeyError) as e:
        raise click.ClickException(str(e))
    click.echo(_json.dumps(rec, indent=1, default=str))


@history_cmd.command("add")
@_history_dir_opt
@click.argument("path", type=click.Path(exists=True))
def history_add_cmd(history_dir, path):
    """Import manifest(s) — a manifest JSON file or a telemetry
    directory — into the history store."""
    from ..observe import history

    if history.history_dir(history_dir) is None:
        raise click.ClickException(
            "no history dir: set BST_HISTORY_DIR or pass --history-dir")
    try:
        ids = history.import_path(path, history_dir)
    except (FileNotFoundError, ValueError) as e:
        raise click.ClickException(str(e))
    for rid in ids:
        click.echo(rid)


@click.command()
@_history_dir_opt
@click.option("--threshold", type=float, default=20.0, show_default=True,
              help="regression threshold in percent (span/byte growth, "
                   "hit-ratio drop in percentage points)")
@click.option("--last", "last_n", type=int, default=None,
              help="diff the N-th most recent record against the most "
                   "recent (--last 2 = previous vs latest; RUN_A/RUN_B "
                   "are then optional). Defaults to records of the SAME "
                   "tool as the latest one — cross-tool deltas compare "
                   "different workloads")
@click.option("--tool", default=None,
              help="restrict --last selection to records of this tool")
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable diff")
@click.option("--fail-on-regression", is_flag=True, default=False,
              help="exit 2 when any regression is flagged (CI gate)")
@click.argument("run_a", required=False)
@click.argument("run_b", required=False)
def perf_diff_cmd(history_dir, threshold, last_n, tool, as_json, run_a,
                  run_b, fail_on_regression):
    """Diff two recorded runs: spans, byte counters, cache hit ratios.

    RUN_A is the baseline, RUN_B the candidate — ids, unique id
    prefixes, negative indices (-1 = latest) or paths to record/manifest
    JSON files. `--last 2` compares the two most recent records of the
    latest record's tool (or of --tool); an explicit RUN_A RUN_B pair
    from different tools diffs with a warning."""
    from ..observe import history

    if last_n is not None:
        if last_n < 2:
            raise click.ClickException("--last wants >= 2 (two runs)")
        try:
            entries = history.list_records(history_dir, tool=tool)
        except FileNotFoundError as e:
            raise click.ClickException(str(e))
        if tool is None and entries:
            # same-tool by default: a fusion vs a solver record diffs
            # syntactically but the deltas are nonsense
            anchor = entries[-1].get("tool")
            same = [e for e in entries if e.get("tool") == anchor]
            if len(same) >= last_n:
                entries = same
            else:
                click.echo(
                    f"warning: only {len(same)} record(s) of tool "
                    f"{anchor!r} — forcing a CROSS-TOOL diff over the "
                    f"whole store (pass --tool to pin one)", err=True)
        if len(entries) < last_n:
            raise click.ClickException(
                f"--last {last_n}: only {len(entries)} matching "
                f"record(s) in the store")
        run_a, run_b = entries[-last_n]["id"], entries[-1]["id"]
    if not run_a or not run_b:
        raise click.ClickException("need RUN_A and RUN_B (or --last 2)")
    try:
        a = history.load_record(run_a, history_dir)
        b = history.load_record(run_b, history_dir)
    except (FileNotFoundError, KeyError, IndexError) as e:
        raise click.ClickException(str(e))
    if a.get("tool") != b.get("tool") and (a.get("tool") or b.get("tool")):
        click.echo(f"warning: cross-tool diff ({a.get('tool')} vs "
                   f"{b.get('tool')}) — the deltas compare different "
                   f"workloads", err=True)
    rep = history.diff(a, b, threshold_pct=threshold)
    if as_json:
        click.echo(_json.dumps(rep, indent=1, default=str))
    else:
        w = rep["wall_clock"]
        click.echo(f"perf-diff {rep['a']}  ->  {rep['b']} "
                   f"(threshold {threshold}%)")
        click.echo(f"wall clock: {w['a_s']}s -> {w['b_s']}s "
                   f"({w['delta_s']:+}s"
                   + (f", {w['delta_pct']:+}%" if w["delta_pct"]
                      is not None else "") + ")")
        changed = [r for r in rep["spans"]
                   if abs(r["delta_s"]) >= 0.001]
        if changed:
            click.echo("spans (total_s):")
            for r in sorted(changed, key=lambda r: -abs(r["delta_s"]))[:20]:
                mark = "  REGRESSION" if r.get("regression") else ""
                pct = (f" ({r['delta_pct']:+}%)"
                       if r["delta_pct"] is not None else "")
                click.echo(f"  {r['span']:<32} {r['a_s']:>9} -> "
                           f"{r['b_s']:>9}  {r['delta_s']:+}s{pct}{mark}")
        moved = [r for r in rep["byte_counters"] if r["delta"]]
        if moved:
            click.echo("byte counters:")
            for r in sorted(moved, key=lambda r: -abs(r["delta"]))[:20]:
                mark = "  REGRESSION" if r.get("regression") else ""
                click.echo(f"  {r['metric']:<48} "
                           f"{_fmt_bytes(r['a'])} -> {_fmt_bytes(r['b'])}"
                           f"{mark}")
        for r in rep["caches"]:
            mark = "  REGRESSION" if r.get("regression") else ""
            click.echo(f"cache {r['cache']}: hit ratio "
                       f"{r['a_hit_ratio']} -> {r['b_hit_ratio']}{mark}")
        n = len(rep["regressions"])
        click.echo(f"{n} regression(s) flagged" if n else
                   "no regressions at this threshold")
    if fail_on_regression and rep["regressions"]:
        sys.exit(2)

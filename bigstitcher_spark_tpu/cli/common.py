"""Shared CLI options (the L4 base-class hierarchy of the reference,
abstractcmdline/*.java, re-expressed as click decorator stacks)."""

from __future__ import annotations


import click

from ..io.spimdata import SpimData


def _set_s3_region(ctx, param, value):
    if value:
        from ..io.uris import set_s3_region

        set_s3_region(value)
    return value


def _register_telemetry_close(ctx):
    """Finalize telemetry exactly once when the command's context closes
    (ctx.params is fully resolved by then, so the manifest records the
    command's actual configuration)."""
    if ctx.meta.get("bst.telemetry.registered"):
        return
    ctx.meta["bst.telemetry.registered"] = True

    def _close():
        import sys

        from .. import observe, profiling
        from ..observe import trace

        # during unwinding from a command error, the in-flight exception is
        # the active one — best-effort status for the manifest
        err = sys.exc_info()[1]
        report = (profiling.get().report()
                  if ctx.meta.get("bst.telemetry.profile") else None)
        traced = trace.enabled()
        if observe.active():
            # finalize archives the trace next to the manifest when on
            observe.finalize(
                tool=ctx.info_name, params=ctx.params,
                status="error" if err is not None else "ok",
                error=repr(err) if err is not None else None)
        if trace.enabled():   # --trace without --telemetry-dir
            trace.finalize()
        if traced and trace.last_path():
            click.echo(f"[trace] {trace.last_path()} "
                       f"(load in ui.perfetto.dev or run "
                       f"'bst trace-report')", err=True)
        if report is not None:
            click.echo(f"[profile]\n{report}", err=True)
            profiling.enable(False)

    ctx.call_on_close(_close)


def _set_telemetry_dir(ctx, param, value):
    if value:
        from .. import observe

        observe.configure(value)
        _register_telemetry_close(ctx)
    return value


def _set_profile(ctx, param, value):
    if value:
        from .. import profiling

        profiling.enable(True)
        ctx.meta["bst.telemetry.profile"] = True
        _register_telemetry_close(ctx)
    return value


def _set_trace(ctx, param, value):
    if value:
        from ..observe import trace

        trace.configure()
        _register_telemetry_close(ctx)
    return value


def infrastructure_options(f):
    """--dryRun / --s3Region (AbstractInfrastructure.java:14-27) plus the
    shared observability switches every tool inherits: --telemetry-dir
    activates the event log / metrics textfile / run manifest
    (observe package), --profile prints the span-stat table at exit."""
    f = click.option("--dryRun", "dry_run", is_flag=True, default=False,
                     help="compute but do not persist results")(f)
    f = click.option("--s3Region", "s3_region", default=None,
                     expose_value=False, callback=_set_s3_region,
                     help="AWS region for s3:// storage roots")(f)
    f = click.option("--telemetry-dir", "telemetry_dir", default=None,
                     expose_value=False, callback=_set_telemetry_dir,
                     help="write a JSONL event log, Prometheus metrics "
                          "textfile and run manifest into this directory "
                          "(one file set per process; merge pod runs with "
                          "'bst telemetry-merge')")(f)
    f = click.option("--profile", is_flag=True, default=False,
                     expose_value=False, callback=_set_profile,
                     help="record per-span wall-clock aggregates and print "
                          "the span table on exit")(f)
    f = click.option("--trace", is_flag=True, default=False,
                     expose_value=False, callback=_set_trace,
                     help="record a begin/end timeline of every span "
                          "(flight recorder, BST_TRACE_BUFFER_BYTES ring) "
                          "and write a Perfetto-loadable trace JSON on "
                          "exit (next to --telemetry-dir files when set, "
                          "else BST_TRACE_PATH / ./bst-trace.json); "
                          "analyze with 'bst trace-report'")(f)
    return f


def _xml_path_ok(ctx, param, value):
    from ..io.uris import has_scheme, strip_file_scheme

    if value is not None and not has_scheme(value):
        import os

        value = strip_file_scheme(value)
        if not os.path.exists(value):
            raise click.BadParameter(f"XML not found: {value}")
    return value


def xml_option(f):
    """-x/--xml; accepts local paths and s3://, gs://, memory:// URIs
    (AbstractBasic.java:43-70 + URITools)."""
    return click.option("-x", "--xml", "xml", required=True,
                        callback=_xml_path_ok,
                        help="path or URI of the SpimData XML project")(f)


def view_selection_options(f):
    """view subset flags (AbstractSelectableViews.java:38-112)."""
    for opt in (
        click.option("--angleId", "angle_ids", default=None,
                     help="comma-separated angle ids to process"),
        click.option("--channelId", "channel_ids", default=None,
                     help="comma-separated channel ids to process"),
        click.option("--illuminationId", "illumination_ids", default=None,
                     help="comma-separated illumination ids to process"),
        click.option("--tileId", "tile_ids", default=None,
                     help="comma-separated tile ids to process"),
        click.option("--timepointId", "timepoint_ids", default=None,
                     help="comma-separated timepoint ids to process"),
        click.option("-vi", "vi", multiple=True,
                     help="explicit view ids 'timepoint,setup' (repeatable)"),
    ):
        f = opt(f)
    return f


def load_project(xml: str) -> SpimData:
    return SpimData.load(xml)


def parse_csv_ints(s: str | None, n: int | None = None) -> list[int] | None:
    if s is None:
        return None
    vals = [int(v) for v in s.split(",")]
    if n is not None and len(vals) != n:
        raise click.BadParameter(f"expected {n} comma-separated ints: {s!r}")
    return vals


def select_views_from_kwargs(sd, kwargs):
    from ..utils.viewselect import select_views

    return select_views(
        sd,
        angle_ids=kwargs.get("angle_ids"),
        channel_ids=kwargs.get("channel_ids"),
        illumination_ids=kwargs.get("illumination_ids"),
        tile_ids=kwargs.get("tile_ids"),
        timepoint_ids=kwargs.get("timepoint_ids"),
        vi=kwargs.get("vi"),
    )

"""Shared CLI options (the L4 base-class hierarchy of the reference,
abstractcmdline/*.java, re-expressed as click decorator stacks)."""

from __future__ import annotations


import click

from ..io.spimdata import SpimData


def _set_s3_region(ctx, param, value):
    if value:
        from ..io.uris import set_s3_region

        set_s3_region(value)
    return value


def infrastructure_options(f):
    """--dryRun / --s3Region (AbstractInfrastructure.java:14-27)."""
    f = click.option("--dryRun", "dry_run", is_flag=True, default=False,
                     help="compute but do not persist results")(f)
    f = click.option("--s3Region", "s3_region", default=None,
                     expose_value=False, callback=_set_s3_region,
                     help="AWS region for s3:// storage roots")(f)
    return f


def _xml_path_ok(ctx, param, value):
    from ..io.uris import has_scheme, strip_file_scheme

    if value is not None and not has_scheme(value):
        import os

        value = strip_file_scheme(value)
        if not os.path.exists(value):
            raise click.BadParameter(f"XML not found: {value}")
    return value


def xml_option(f):
    """-x/--xml; accepts local paths and s3://, gs://, memory:// URIs
    (AbstractBasic.java:43-70 + URITools)."""
    return click.option("-x", "--xml", "xml", required=True,
                        callback=_xml_path_ok,
                        help="path or URI of the SpimData XML project")(f)


def view_selection_options(f):
    """view subset flags (AbstractSelectableViews.java:38-112)."""
    for opt in (
        click.option("--angleId", "angle_ids", default=None,
                     help="comma-separated angle ids to process"),
        click.option("--channelId", "channel_ids", default=None,
                     help="comma-separated channel ids to process"),
        click.option("--illuminationId", "illumination_ids", default=None,
                     help="comma-separated illumination ids to process"),
        click.option("--tileId", "tile_ids", default=None,
                     help="comma-separated tile ids to process"),
        click.option("--timepointId", "timepoint_ids", default=None,
                     help="comma-separated timepoint ids to process"),
        click.option("-vi", "vi", multiple=True,
                     help="explicit view ids 'timepoint,setup' (repeatable)"),
    ):
        f = opt(f)
    return f


def load_project(xml: str) -> SpimData:
    return SpimData.load(xml)


def parse_csv_ints(s: str | None, n: int | None = None) -> list[int] | None:
    if s is None:
        return None
    vals = [int(v) for v in s.split(",")]
    if n is not None and len(vals) != n:
        raise click.BadParameter(f"expected {n} comma-separated ints: {s!r}")
    return vals


def select_views_from_kwargs(sd, kwargs):
    from ..utils.viewselect import select_views

    return select_views(
        sd,
        angle_ids=kwargs.get("angle_ids"),
        channel_ids=kwargs.get("channel_ids"),
        illumination_ids=kwargs.get("illumination_ids"),
        tile_ids=kwargs.get("tile_ids"),
        timepoint_ids=kwargs.get("timepoint_ids"),
        vi=kwargs.get("vi"),
    )

"""``stitching`` command (SparkPairwiseStitching equivalent).

Distributed FFT phase-correlation translation estimation for every
overlapping tile pair; results (+ registration hash) land in the XML's
StitchingResults section for the solver. Flags mirror the reference
(SparkPairwiseStitching.java:76-106).
"""

from __future__ import annotations

import numpy as np
import click

from ..io.dataset_io import ViewLoader
from ..io.spimdata import SpimData
from ..models.stitching import (
    StitchingParams,
    filter_results,
    stitch_all_pairs,
    store_results,
)
from .common import (
    infrastructure_options,
    parse_csv_ints,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("-ds", "--downsampling", "downsampling", default="2,2,1",
              help="downsampling for the correlation, e.g. 4,4,1")
@click.option("-p", "--peaksToCheck", "peaks", type=int, default=5,
              help="phase-correlation peaks to verify by cross-correlation")
@click.option("--disableSubpixelResolution", "no_subpixel", is_flag=True,
              default=False)
@click.option("--minR", "min_r", type=float, default=0.3,
              help="minimum required cross correlation")
@click.option("--maxR", "max_r", type=float, default=1.0)
@click.option("--maxShiftX", "max_shift_x", type=float, default=None)
@click.option("--maxShiftY", "max_shift_y", type=float, default=None)
@click.option("--maxShiftZ", "max_shift_z", type=float, default=None)
@click.option("--maxShiftTotal", "max_shift_total", type=float, default=None)
@click.option("--channelCombine", "channel_combine",
              type=click.Choice(["AVERAGE", "PICK_BRIGHTEST"]),
              default="AVERAGE")
@click.option("--illumCombine", "illum_combine",
              type=click.Choice(["AVERAGE", "PICK_BRIGHTEST"]),
              default="PICK_BRIGHTEST")
def stitching_cmd(xml, downsampling, peaks, no_subpixel, min_r, max_r,
                  max_shift_x, max_shift_y, max_shift_z, max_shift_total,
                  channel_combine, illum_combine, dry_run, **kwargs):
    """Pairwise phase-correlation stitching of overlapping tiles."""
    sd = SpimData.load(xml)
    loader = ViewLoader(sd)
    views = select_views_from_kwargs(sd, kwargs)

    inf = float("inf")
    params = StitchingParams(
        downsampling=tuple(parse_csv_ints(downsampling, 3)),
        peaks_to_check=peaks,
        subpixel=not no_subpixel,
        min_r=min_r, max_r=max_r,
        max_shift=(max_shift_x if max_shift_x is not None else inf,
                   max_shift_y if max_shift_y is not None else inf,
                   max_shift_z if max_shift_z is not None else inf),
        max_shift_total=(max_shift_total if max_shift_total is not None else inf),
        channel_combine=channel_combine,
        illum_combine=illum_combine,
    )
    results = stitch_all_pairs(sd, loader, views, params)
    for res in results:
        shift = res.transform[:, 3]
        click.echo(f"  {res.views_a} <-> {res.views_b}: "
                   f"shift={np.round(shift, 2)} r={res.correlation:.3f}")
    kept = filter_results(results, params)
    click.echo(f"{len(kept)}/{len(results)} pairs pass filters")
    if dry_run:
        click.echo("(dry run, not saving)")
        return
    store_results(sd, kept, computed=results)
    sd.save(xml)
    click.echo(f"saved StitchingResults -> {xml}")

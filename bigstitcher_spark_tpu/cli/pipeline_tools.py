"""``bst pipeline`` — run whole stage DAGs through the streaming executor.

``bst pipeline run <spec.json>`` executes every stage in ONE process:
consumers start the moment their input blocks exist, blocks hand over in
memory, and ephemeral intermediates never touch disk. ``bst pipeline
init`` writes a runnable example spec for a project XML; ``bst submit
--pipeline <spec.json>`` runs the same spec inside a resident `bst
serve` daemon (warm mesh + caches across pipelines)."""

from __future__ import annotations

import json as _json
import os

import click

from .common import infrastructure_options
from .telemetry_tools import _fmt_bytes


@click.group("pipeline")
def pipeline_cmd():
    """Streaming block-granular stage-DAG pipelines."""


@pipeline_cmd.command("run")
@infrastructure_options
@click.argument("spec", type=click.Path(exists=True, dir_okay=False))
@click.option("--workdir", default=None,
              help="directory relative dataset paths and @workdir resolve "
                   "against (default: the spec file's directory)")
@click.option("--keep-intermediates", "keep", is_flag=True, default=False,
              help="materialize ephemeral datasets at their declared "
                   "paths and keep them after the run (default: elide "
                   "them to in-process memory:// roots, cleaned up on "
                   "success and on failure)")
@click.option("--json", "as_json", is_flag=True, default=False,
              help="print the machine-readable run summary (interleaved "
                   "with the stages' own output — scripts should prefer "
                   "--summary)")
@click.option("--summary", "summary_path", default=None,
              type=click.Path(dir_okay=False),
              help="also write the machine-readable run summary JSON to "
                   "this file (stage stdout cannot pollute it)")
def run_cmd(spec, workdir, keep, as_json, summary_path, dry_run):
    """Execute the pipeline SPEC (stage nodes + dataset edges, JSON)."""
    from ..dag import PipelineSpec, SpecError, run_pipeline

    try:
        ps = PipelineSpec.load(spec)
    except SpecError as e:
        raise click.ClickException(str(e)) from e
    if dry_run:
        click.echo(f"pipeline {ps.name}: {len(ps.stages)} stage(s)")
        for s in ps.stages:
            deps = sorted(ps.barrier_parents(s))
            sdeps = sorted(ps.stream_parents(s))
            line = f"  {s.id}: {s.tool}"
            if deps:
                line += f"  after={','.join(deps)}"
            if sdeps:
                line += f"  streams-from={','.join(sdeps)}"
            click.echo(line)
        click.echo("(dry run, not executing)")
        return
    try:
        res = run_pipeline(
            ps, workdir=workdir or os.path.dirname(os.path.abspath(spec)),
            keep_intermediates=keep)
    except SpecError as e:
        raise click.ClickException(str(e)) from e
    if summary_path:
        with open(summary_path, "w", encoding="utf-8") as fh:
            _json.dump(res.to_dict(), fh, indent=1)
            fh.write("\n")
    if as_json:
        click.echo(_json.dumps(res.to_dict(), indent=1))
    else:
        click.echo(f"pipeline {res.name}:")
        for row in res.stages:
            line = f"  {row['id']:<12} {row['state']:<10}"
            if "seconds" in row:
                line += f" {row['seconds']}s"
            if row.get("error"):
                line += f"  {row['error']}"
            click.echo(line)
        for e in res.edges:
            tag = "elided container" if e["elided"] else (
                "streamed" if e["stream"] else "barrier")
            click.echo(
                f"  edge {e['edge']}: {e['blocks_streamed']} blocks "
                f"streamed, {_fmt_bytes(e['bytes_elided'])} handed over "
                f"in memory, {_fmt_bytes(e['bytes_reread'])} re-read "
                f"({tag})")
        click.echo(f"  {res.seconds:.1f}s total; "
                   f"{res.containers_elided} intermediate container(s) "
                   f"elided")
    if not res.ok:
        bad = [r["id"] for r in res.stages if r["state"] != "done"]
        raise click.ClickException(f"stage(s) failed/cancelled: "
                                   f"{', '.join(bad)}")


@pipeline_cmd.command("init")
@click.argument("out", type=click.Path(dir_okay=False))
@click.option("-x", "--xml", "xml", required=True,
              type=click.Path(exists=True, dir_okay=False),
              help="project XML the generated pipeline processes")
@click.option("--prefix", default="pipeline",
              help="name prefix for the pipeline's containers/XML "
                   "(written next to the project XML)")
@click.option("--force", is_flag=True, default=False,
              help="overwrite an existing spec file")
@click.option("--registration", "registration", is_flag=True, default=False,
              help="write the registration-round spec instead (detect -> "
                   "match -> solve, the solver barrier-gated on the "
                   "matcher's correspondences)")
@click.option("--label", default="beads",
              help="interest-point label the registration spec uses")
def init_cmd(out, xml, prefix, force, registration, label):
    """Write a runnable example spec (streamed resave -> fuse ->
    downsample -> detect; with --registration the detect -> match ->
    solve round) for the project XML to OUT."""
    from ..dag import PipelineSpec, example_spec, registration_spec

    if os.path.exists(out) and not force:
        raise click.ClickException(f"{out} exists (use --force)")
    if registration:
        d = registration_spec(xml, prefix=prefix, label=label)
    else:
        d = example_spec(xml, prefix=prefix)
    PipelineSpec.from_dict(d)   # never emit a spec that does not validate
    with open(out, "w", encoding="utf-8") as f:
        _json.dump(d, f, indent=1)
        f.write("\n")
    click.echo(f"wrote {out} ({len(d['stages'])} stages); run it with "
               f"`bst pipeline run {out}` or submit it to a daemon with "
               f"`bst submit --pipeline {out}`")

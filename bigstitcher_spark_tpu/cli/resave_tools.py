"""``resave`` and ``downsample`` commands.

Reference tools: SparkResaveN5.java (re-save any dataset into chunked
N5/OME-ZARR + pyramid, rewiring the XML) and SparkDownsample.java
(distributed pyramid creation for an existing dataset). Flag names follow
the reference CLI surface (SparkResaveN5.java:80-104,
SparkDownsample.java:60-76).
"""

from __future__ import annotations

import os
import shutil

import click
import numpy as np

from ..io.chunkstore import ChunkStore, StorageFormat
from ..io.dataset_io import ViewLoader
from ..io.spimdata import SpimData
from ..models.downsample_driver import (
    _convert_to_dtype,
    read_padded,
    run_sharded_downsample,
    validate_pyramid,
)
from ..models.resave import propose_pyramid, resave, swap_imgloader
from ..utils.grid import create_grid
from .common import (
    infrastructure_options,
    parse_csv_ints,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


def parse_pyramid(spec_list) -> list[list[int]] | None:
    """Parse ``-ds 1,1,1 -ds 2,2,1`` or a single ``'1,1,1; 2,2,1'`` string
    (reference ';'-separated pyramid specs, Import.java:261-287)."""
    if not spec_list:
        return None
    parts: list[str] = []
    for s in spec_list:
        parts.extend(p for p in s.split(";") if p.strip())
    return [parse_csv_ints(p.strip(), 3) for p in parts]


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("-xo", "--xmlout", "xml_out", default=None,
              help="output XML path (default: overwrite input, keep ~1 backup)")
@click.option("-o", "--n5Path", "out_path", default=None,
              help="container path (default: '<xml folder>/dataset-resaved"
                   ".n5|.zarr')")
@click.option("--N5", "as_n5", is_flag=True, default=False,
              help="export as N5 (default: ZARR)")
@click.option("--blockSize", "block_size", default="128,128,64")
@click.option("--blockScale", "block_scale", default="16,16,1",
              help="how many blocks one processing step writes")
@click.option("-ds", "--downsampling", "downsampling", multiple=True,
              help="pyramid steps incl. 1,1,1, e.g. '1,1,1; 2,2,1; 4,4,1'")
@click.option("-c", "--compression", default="zstd",
              type=click.Choice(["zstd", "gzip", "raw", "blosc", "bzip2", "xz",
                                 "lz4"]))
@click.option("-cl", "--compressionLevel", "compression_level", type=int,
              default=None,
              help="codec-specific compression level (SparkResaveN5 -cl)")
@click.option("--threads", type=int, default=8,
              help="host IO threads for block copy")
def resave_cmd(xml, xml_out, out_path, as_n5, block_size, block_scale,
               downsampling, compression, compression_level, dry_run,
               threads, **kwargs):
    """Re-save the project into a chunked multi-res container."""
    if compression_level is not None:
        compression = f"{compression}:{compression_level}"
    sd = SpimData.load(xml)
    loader = ViewLoader(sd)
    views = select_views_from_kwargs(sd, kwargs)
    storage_format = StorageFormat.N5 if as_n5 else StorageFormat.ZARR
    if compression in ("xz", "lz4") and storage_format != StorageFormat.N5:
        raise click.ClickException(
            f"{compression} compression is only available for N5 containers (--N5)")
    if out_path is None:
        ext = "n5" if as_n5 else "zarr"
        out_path = os.path.join(os.path.dirname(os.path.abspath(xml)),
                                f"dataset-resaved.{ext}")
    ds = parse_pyramid(downsampling) or propose_pyramid(sd, views)
    validate_pyramid(ds)  # preflight so --dryRun catches bad pyramids too
    bs = tuple(parse_csv_ints(block_size, 3))
    bsc = tuple(parse_csv_ints(block_scale, 3))
    click.echo(f"resaving {len(views)} views -> {out_path} ({storage_format.value})")
    click.echo(f"pyramid: {ds}")
    if dry_run:
        click.echo("(dry run, not writing)")
        return
    stats = resave(
        sd, loader, views, out_path, storage_format,
        block_size=bs, block_scale=bsc,
        downsamplings=ds, compression=compression, threads=threads,
    )
    from ..io import uris

    # abspath only LOCAL outputs — os.path.abspath would mangle a cloud URI
    # into '<cwd>/s3:/...' (r5: caught by the real-s3 endpoint test)
    swap_imgloader(sd,
                   out_path if uris.has_scheme(out_path)
                   else os.path.abspath(out_path),
                   storage_format)
    target = xml_out or xml
    if xml_out is None and os.path.exists(xml):
        shutil.copy2(xml, xml + "~1")  # reference keeps a ~1 backup
    sd.save(target)
    click.echo(
        f"resaved {stats.views} views ({stats.s0_blocks} s0 + "
        f"{stats.pyramid_blocks} pyramid blocks) in {stats.seconds:.1f}s; "
        f"XML -> {target}"
    )


@click.command()
@infrastructure_options
@click.option("-i", "--n5PathIn", "path_in", required=True,
              help="container path, e.g. /home/fused.n5")
@click.option("-di", "--n5DatasetIn", "dataset_in", required=True,
              help="input dataset, e.g. /ch488/s0")
@click.option("-do", "--n5DatasetsOut", "datasets_out", default=None,
              help="output dataset(s), ';'-separated, e.g. /ch488/s1;/ch488/s2")
@click.option("-ds", "--downsampling", "downsampling", required=True,
              help="consecutive steps, ';'-separated, e.g. '2,2,1; 2,2,1; 2,2,2'")
@click.option("-s", "--storage", "storage_opt", default=None,
              type=click.Choice(["N5", "ZARR", "HDF5"]),
              help="container storage format (validated against the path)")
@click.option("--blockScale", "block_scale", default="1,1,1")
@click.option("--threads", type=int, default=8)
@click.option("--skip-existing", "skip_existing", is_flag=True, default=False,
              help="skip steps whose output dataset already exists with "
                   "matching dimensions and downsampling factors (e.g. "
                   "levels a fusion --pyramid epilogue materialized)")
def downsample_cmd(path_in, dataset_in, datasets_out, downsampling,
                   storage_opt, block_scale, threads, skip_existing,
                   dry_run):
    """Chained 2x downsampling of an existing dataset (pyramid levels)."""
    if storage_opt is not None:
        fmt = ChunkStore.open(path_in).format
        if fmt != StorageFormat(storage_opt):
            raise click.ClickException(
                f"--storage {storage_opt} does not match the container at "
                f"{path_in} ({fmt.name})")
    store = ChunkStore.open(path_in)
    src_path = dataset_in.strip("/")
    steps = parse_pyramid([downsampling])
    if datasets_out:
        outs = [p.strip().strip("/") for p in datasets_out.split(";") if p.strip()]
    else:
        # default: s{N} siblings of the input (reference requires -do; we
        # derive it when the input ends in s{N})
        base, name = os.path.split(src_path)
        if not (name.startswith("s") and name[1:].isdigit()):
            raise click.ClickException("-do required unless input ends in /s<N>")
        n0 = int(name[1:])
        outs = [f"{base}/s{n0 + i + 1}".strip("/") for i in range(len(steps))]
    if len(outs) != len(steps):
        raise click.ClickException(
            f"{len(outs)} output datasets but {len(steps)} downsampling steps"
        )

    src = store.open_dataset(src_path)
    if len(src.shape) != 3:
        raise click.ClickException(
            f"{src_path} is {len(src.shape)}-D; this tool handles 3-D "
            "datasets (5-D OME-ZARR fusion pyramids are written by "
            "affine-fusion itself)"
        )
    bscale = parse_csv_ints(block_scale, 3)
    click.echo(f"downsampling {src_path} {src.shape} by {steps} -> {outs}")
    if dry_run:
        return

    prev = src
    # absolute factors continue from the input level's own factors so
    # best_mipmap_level / mipmap transforms stay correct when starting at s>0
    abs_factor = [int(v) for v in
                  (store.get_attribute(src_path, "downsamplingFactors")
                   or [1, 1, 1])]
    abs_factors: list[list[int]] = []  # one per output level, for registration
    for step, out_path in zip(steps, outs):
        abs_factor = [a * f for a, f in zip(abs_factor, step)]
        abs_factors.append(list(abs_factor))
        dims = [max(1, s // f) for s, f in zip(prev.shape, step)]
        if skip_existing and store.is_dataset(out_path):
            ex = store.open_dataset(out_path)
            exf = store.get_attribute(out_path, "downsamplingFactors")
            if (list(ex.shape) == dims and exf is not None
                    and [int(v) for v in exf] == [int(v) for v in abs_factor]):
                click.echo(f"  {out_path} {tuple(dims)} already exists with "
                           "matching factors, skipped")
                prev = ex
                continue
        dst = store.create_dataset(out_path, dims, prev.block_size,
                                   prev.dtype.name, delete_existing=True)
        store.set_attribute(out_path, "downsamplingFactors",
                            [int(v) for v in abs_factor])
        compute_block = [b * s for b, s in zip(dst.block_size, bscale)]
        grid = create_grid(dims, compute_block, dst.block_size)

        def read_job(blk, src_ds=prev, f=tuple(step)):
            src_off = [o * x for o, x in zip(blk.offset, f)]
            src_size = [s * x for s, x in zip(blk.size, f)]

            def rd(off, size):
                # a streamed producer's device-resident blocks serve
                # straight from HBM (zero D2H + zero container decode);
                # None falls back to the gated host read
                dev = src_ds.read_device(off, size)
                return dev if dev is not None else src_ds.read(off, size)

            return read_padded(rd, src_ds.shape, src_off, src_size)

        def write_job(blk, out, dst_ds=dst):
            dst_ds.write(_convert_to_dtype(out, dst_ds.dtype), blk.offset)

        run_sharded_downsample(grid, read_job, write_job, tuple(step),
                               io_threads=threads,
                               label=f"downsample block ({out_path})",
                               device_drain=store.format
                               != StorageFormat.HDF5)
        click.echo(f"  wrote {out_path} {tuple(dims)}")
        prev = dst

    # BDV layout (setup{S}/timepoint{T}/s{N}): extend the setup-level factor
    # list so ViewLoader/best_mipmap_level can discover the new levels.
    # ViewLoader resolves level i -> dataset s{i}, so a factor may only be
    # registered when its output leaf IS s{len(list)} at registration time.
    parts = src_path.split("/")
    if (len(parts) == 3 and parts[0].startswith("setup")
            and all(p.strip("/").split("/")[0] == parts[0]
                    and len(p.strip("/").split("/")) == 3 for p in outs)):
        setup_group = parts[0]
        existing = store.get_attribute(setup_group, "downsamplingFactors") or []
        existing = [list(map(int, f)) for f in existing]
        if not existing and parts[2] == "s0":
            # fresh single-scale dataset: seed the list with the input level
            existing = [[int(v) for v in
                         (store.get_attribute(src_path, "downsamplingFactors")
                          or [1, 1, 1])]]
        added, skipped = [], []
        for out_path, af in zip(outs, abs_factors):
            leaf = out_path.split("/")[-1]
            if af in existing and leaf == f"s{existing.index(af)}":
                continue  # already registered at the matching index
            if leaf == f"s{len(existing)}":
                existing.append(af)
                added.append(af)
            else:
                skipped.append(out_path)
        if added:
            store.set_attribute(setup_group, "downsamplingFactors", existing)
            store.set_attribute(f"{setup_group}/{parts[1]}", "multiScale", True)
            click.echo(f"  registered factors {added} on {setup_group}")
        for p in skipped:
            click.echo(f"  WARNING: {p} not registered on {setup_group} — its "
                       f"s<N> index does not continue the existing level list "
                       f"(levels must be consecutive s0..s{len(existing) - 1})")

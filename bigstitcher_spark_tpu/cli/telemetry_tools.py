"""``telemetry-merge`` / ``trace-report`` — fold and analyze run telemetry.

No reference counterpart (Spark's history server renders the merged view
of its event logs); here N per-process ``manifest-*.json`` /
``events-*.jsonl`` file sets written by ``--telemetry-dir`` fold into one
``merged-report.json`` plus a console summary: per-process status, the
summed metric/byte totals, the merged span table and per-stage
throughput. Flight-recorder traces (``--trace``) fold alongside —
per-process ``trace-*.json`` files are clock-aligned via the shared
barrier exits into ``merged-trace.json`` — and ``bst trace-report``
computes what the aggregates cannot: compute/D2H/write/idle
decomposition, pairwise overlap, per-device idle gaps and the
per-block critical path (analysis/tracereport.py).
"""

from __future__ import annotations

import json
import os

import click


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


@click.command()
@click.argument("telemetry_dir",
                type=click.Path(exists=True, file_okay=False))
@click.option("-o", "--output", "output", default=None,
              help="merged report path (default: "
                   "<telemetry_dir>/merged-report.json)")
def telemetry_merge_cmd(telemetry_dir, output):
    """Merge per-process telemetry files into one run report."""
    from ..observe.manifest import merge_run

    try:
        report = merge_run(telemetry_dir)
    except FileNotFoundError as e:
        raise click.ClickException(str(e)) from e
    out = output or os.path.join(telemetry_dir, "merged-report.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, default=str)
        f.write("\n")

    procs = report["processes"]
    click.echo(f"run: {len(procs)} manifest(s), "
               f"{report['process_count']} process(es), "
               f"wall clock {report['wall_clock_s']}s, "
               f"{report['events']} events")
    for p in procs:
        dev = p.get("device", {})
        click.echo(f"  [{p.get('process_index')}] {p.get('tool')} "
                   f"{p.get('status')} in {p.get('seconds')}s "
                   f"({dev.get('platform', '?')} x"
                   f"{dev.get('local_device_count', '?')})")
    if report["stages"]:
        click.echo("stages:")
        for s in report["stages"]:
            rate = s.get("rate_per_s")
            eta = s.get("eta_error_s")
            secs = s.get("seconds")
            secs = round(secs, 3) if isinstance(secs, float) else secs
            click.echo(
                f"  {s['stage']}: {s.get('done', '?')}/{s.get('total', '?')} "
                f"items in {secs}s"
                + (f" ({round(rate, 3)}/s)" if rate is not None else "")
                + (f", ETA error {eta:+.1f}s" if eta is not None else ""))
    m = report["metrics"]

    def _total(prefix):
        return sum(v for k, v in m.items()
                   if k.startswith(prefix) and isinstance(v, (int, float)))

    click.echo(
        "io: read "
        f"{_fmt_bytes(_total('bst_io_read_bytes_total'))}, write "
        f"{_fmt_bytes(_total('bst_io_write_bytes_total'))}, h2d "
        f"{_fmt_bytes(_total('bst_xfer_h2d_bytes_total'))}, d2h "
        f"{_fmt_bytes(_total('bst_xfer_d2h_bytes_total'))}")
    if report["failures_by_exception"]:
        click.echo("failures by exception: " + ", ".join(
            f"{k} x{v}" for k, v in
            sorted(report["failures_by_exception"].items(),
                   key=lambda kv: -kv[1])))
    retries = _total("bst_retry_rounds_total")
    if retries:
        click.echo(f"retry rounds: {int(retries)}")
    click.echo(f"merged report -> {out}")

    # with a history store configured, the merged POD manifest records
    # there too, so `bst history` / `bst perf-diff` cover pod runs, not
    # only single-process finalize paths; history IO never fails a merge
    try:
        from ..observe.history import record_merged_report

        rid = record_merged_report(report, source=out)
    except Exception:
        rid = None
    if rid:
        click.echo(f"recorded in history as {rid} "
                   f"(diff pod runs with 'bst perf-diff')")

    # fold any per-process flight-recorder traces onto one barrier-aligned
    # timeline so trace-report / Perfetto see the whole pod run at once
    from ..observe.trace import merge_traces

    try:
        merged_trace = merge_traces(telemetry_dir)
    except (json.JSONDecodeError, OSError) as e:
        click.echo(f"trace merge skipped (corrupt/unreadable trace: {e})",
                   err=True)
        merged_trace = None
    if merged_trace:
        unaligned = merged_trace.bst.get("unaligned_processes")
        if unaligned:
            click.echo(f"WARNING: processes {unaligned} had no barrier "
                       f"exits in common with process 0 — their clocks "
                       f"are UNALIGNED in the merged trace", err=True)
        click.echo(f"merged trace -> {merged_trace} "
                   f"(analyze with 'bst trace-report', or load in "
                   f"ui.perfetto.dev)")


@click.command()
@click.argument("path", type=click.Path(exists=True))
@click.option("--top", "top", type=int, default=5, show_default=True,
              help="how many blocking segments of the critical path to name")
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable report")
def trace_report_cmd(path, top, as_json):
    """Analyze a --trace timeline: overlap, idle gaps, critical path.

    PATH is a trace JSON file or a telemetry directory (prefers
    merged-trace.json, else every trace-*.json in it). Prints each
    stage's wall clock decomposed into compute/D2H/write/idle union
    time, the pairwise overlap percentages between them (is D2H hiding
    under the writes?), per-device/per-thread busy time and the largest
    idle gaps, and the critical path over per-block causal chains
    (dispatch -> kernel -> d2h -> write) with its top blocking segments.
    """
    from ..analysis.tracereport import (
        build_report, load_events, render_report,
    )

    try:
        events, meta = load_events(path)
    except FileNotFoundError as e:
        raise click.ClickException(str(e)) from e
    except json.JSONDecodeError as e:
        # traces are written at process exit — an OOM-kill mid-dump
        # leaves a half-written file
        raise click.ClickException(
            f"corrupt trace JSON under {path}: {e}") from e
    if meta.get("unmerged"):
        click.echo(f"WARNING: analyzing {len(meta['files'])} per-process "
                   f"traces on their RAW host clocks — run "
                   f"'bst telemetry-merge' first to barrier-align them; "
                   f"cross-process overlap/idle/critical-path numbers "
                   f"below are skewed by any clock offset", err=True)
    if meta.get("unaligned_processes"):
        click.echo(f"WARNING: processes {meta['unaligned_processes']} had "
                   f"no barrier exits in common with process 0 — their "
                   f"clocks are unaligned in this trace", err=True)
    report = build_report(events, meta, top=top)
    if as_json:
        click.echo(json.dumps(report, indent=1, default=str))
    else:
        click.echo(f"trace files: {', '.join(meta['files'])}")
        click.echo(render_report(report))

"""``telemetry-merge`` — fold a pod run's per-process telemetry files.

No reference counterpart (Spark's history server renders the merged view
of its event logs); here N per-process ``manifest-*.json`` /
``events-*.jsonl`` file sets written by ``--telemetry-dir`` fold into one
``merged-report.json`` plus a console summary: per-process status, the
summed metric/byte totals, the merged span table and per-stage
throughput.
"""

from __future__ import annotations

import json
import os

import click


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


@click.command()
@click.argument("telemetry_dir",
                type=click.Path(exists=True, file_okay=False))
@click.option("-o", "--output", "output", default=None,
              help="merged report path (default: "
                   "<telemetry_dir>/merged-report.json)")
def telemetry_merge_cmd(telemetry_dir, output):
    """Merge per-process telemetry files into one run report."""
    from ..observe.manifest import merge_run

    try:
        report = merge_run(telemetry_dir)
    except FileNotFoundError as e:
        raise click.ClickException(str(e)) from e
    out = output or os.path.join(telemetry_dir, "merged-report.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, default=str)
        f.write("\n")

    procs = report["processes"]
    click.echo(f"run: {len(procs)} manifest(s), "
               f"{report['process_count']} process(es), "
               f"wall clock {report['wall_clock_s']}s, "
               f"{report['events']} events")
    for p in procs:
        dev = p.get("device", {})
        click.echo(f"  [{p.get('process_index')}] {p.get('tool')} "
                   f"{p.get('status')} in {p.get('seconds')}s "
                   f"({dev.get('platform', '?')} x"
                   f"{dev.get('local_device_count', '?')})")
    if report["stages"]:
        click.echo("stages:")
        for s in report["stages"]:
            rate = s.get("rate_per_s")
            eta = s.get("eta_error_s")
            secs = s.get("seconds")
            secs = round(secs, 3) if isinstance(secs, float) else secs
            click.echo(
                f"  {s['stage']}: {s.get('done', '?')}/{s.get('total', '?')} "
                f"items in {secs}s"
                + (f" ({round(rate, 3)}/s)" if rate is not None else "")
                + (f", ETA error {eta:+.1f}s" if eta is not None else ""))
    m = report["metrics"]

    def _total(prefix):
        return sum(v for k, v in m.items()
                   if k.startswith(prefix) and isinstance(v, (int, float)))

    click.echo(
        "io: read "
        f"{_fmt_bytes(_total('bst_io_read_bytes_total'))}, write "
        f"{_fmt_bytes(_total('bst_io_write_bytes_total'))}, h2d "
        f"{_fmt_bytes(_total('bst_xfer_h2d_bytes_total'))}, d2h "
        f"{_fmt_bytes(_total('bst_xfer_d2h_bytes_total'))}")
    if report["failures_by_exception"]:
        click.echo("failures by exception: " + ", ".join(
            f"{k} x{v}" for k, v in
            sorted(report["failures_by_exception"].items(),
                   key=lambda kv: -kv[1])))
    retries = _total("bst_retry_rounds_total")
    if retries:
        click.echo(f"retry rounds: {int(retries)}")
    click.echo(f"merged report -> {out}")

"""CLI face of the serve daemon: ``bst serve`` / ``submit`` / ``jobs`` /
``cancel``.

The daemon owns the mesh and the caches; these commands are thin clients
over its Unix-domain socket (BST_SERVE_SOCKET / --socket), so a pipeline
script swaps ``bst affine-fusion ...`` for ``bst submit affine-fusion
...`` and stops paying jax init + compile per stage."""

from __future__ import annotations

import json as _json
import sys

import click


def _socket_opt(f):
    return click.option("--socket", "socket_path", default=None,
                        help="daemon Unix socket (default: "
                             "BST_SERVE_SOCKET or the per-user temp-dir "
                             "path)")(f)


@click.command()
@_socket_opt
@click.option("--slots", type=int, default=None,
              help="concurrent job slots (default: BST_SERVE_SLOTS); "
                   "derived byte-window budgets split across slots")
@click.option("--jobs-root", "jobs_root", default=None,
              help="directory for per-job telemetry (events/manifest/"
                   "output.log per job; default: <socket>-jobs)")
@click.option("--idle-timeout", "idle_timeout", type=int, default=None,
              help="exit after this many idle seconds "
                   "(default: BST_SERVE_IDLE_TIMEOUT; 0 = never)")
@click.option("--metrics-port", "metrics_port", type=int, default=None,
              help="port of the live HTTP exporter (/metrics /healthz "
                   "/status /jobs on 127.0.0.1); 0 picks a free port, "
                   "default: BST_METRICS_PORT (whose 0 means off)")
@click.option("--relay", "relay", default=None, metavar="HOST:PORT",
              help="host the pod telemetry collector at this address "
                   "(default: BST_TELEMETRY_RELAY): relayed worker ranks "
                   "feed the daemon's /metrics, /healthz, /cluster and "
                   "`bst top --cluster`; port 0 picks a free one")
@click.option("--detach", is_flag=True, default=False,
              help="start the daemon as a background process and return "
                   "once it answers ping")
@click.option("--stop", is_flag=True, default=False,
              help="ask the daemon on --socket to drain and exit")
@click.option("--status", is_flag=True, default=False,
              help="ping the daemon and print its status")
def serve_cmd(socket_path, slots, jobs_root, idle_timeout, metrics_port,
              relay, detach, stop, status):
    """Run (or manage) the persistent stitching daemon.

    The daemon owns the device mesh and every process-wide cache
    (decoded-chunk LRU, HBM tile cache, compiled-fn buckets); jobs
    submitted with `bst submit` execute in-process with per-job config /
    telemetry / cancellation scoping, so repeat submissions hit warm
    compile caches instead of paying cold start."""
    from ..serve import client, daemon

    if stop:
        client.shutdown(socket_path, drain=True)
        click.echo("serve: drain requested")
        return
    if status:
        click.echo(_json.dumps(client.ping(socket_path), indent=1))
        return
    if detach:
        from .. import config

        pid = daemon.spawn_detached(socket_path, slots=slots,
                                    jobs_root=jobs_root,
                                    idle_timeout=idle_timeout,
                                    metrics_port=metrics_port,
                                    relay=relay)
        pong = client.ping(socket_path)
        port = pong.get("metrics_port")
        rly = pong.get("relay")
        # the child daemon inherits this environment, so the exporter
        # bound the same BST_METRICS_HOST this process resolves
        from ..observe.httpexport import display_host

        host = display_host(config.get_str("BST_METRICS_HOST"))
        click.echo(f"serve: daemon ready (pid {pid})"
                   + (f", live exporter http://{host}:{port}"
                      if port else "")
                   + (f", relay collector {rly}" if rly else ""))
        return
    daemon.run_foreground(socket_path, slots=slots, jobs_root=jobs_root,
                          idle_timeout=idle_timeout,
                          metrics_port=metrics_port, relay=relay)


def _parse_sets(pairs) -> dict:
    out = {}
    for p in pairs:
        if "=" not in p:
            raise click.BadParameter(f"--set wants BST_NAME=value: {p!r}")
        k, v = p.split("=", 1)
        out[k.strip()] = v
    return out


@click.command(context_settings={"ignore_unknown_options": True})
@_socket_opt
@click.option("--priority", type=int, default=0,
              help="higher runs first (strict)")
@click.option("--share", default=None,
              help="fair-share identity; submitters with less accumulated "
                   "runtime go first within a priority band")
@click.option("--set", "sets", multiple=True, metavar="BST_NAME=VALUE",
              help="per-job config override (repeatable; any declared "
                   "BST_* knob — the job sees this value, the daemon and "
                   "other jobs do not)")
@click.option("--cost", type=float, default=1.0,
              help="relative cost for LPT slot placement")
@click.option("--after", "after", multiple=True,
              metavar="JOB-ID[,JOB-ID...]",
              help="dependency edge(s): stay queued until these jobs "
                   "succeed; cancel if any of them fails or is cancelled "
                   "(repeatable / comma-separated)")
@click.option("--pipeline", "pipeline_spec", default=None,
              type=click.Path(exists=True, dir_okay=False),
              help="submit a whole pipeline spec (see `bst pipeline`) as "
                   "one daemon job — stages chain on the daemon's warm "
                   "mesh and caches, streaming blocks between them "
                   "in-process; TOOL/ARGS become extra `pipeline run` "
                   "flags (e.g. --keep-intermediates)")
@click.option("--profile", "profile", default=None,
              metavar="auto|KEY",
              help="apply a tuned profile from the daemon's history "
                   "store (`bst tune run` writes them): a profile key / "
                   "unique prefix, or `auto` to let the daemon pick the "
                   "best backend/device/shape match; profile knobs apply "
                   "under any explicit --set")
@click.option("--follow/--no-follow", default=True,
              help="stream heartbeats and exit with the job's exit code "
                   "(default) vs. return the job id immediately")
@click.option("--quiet", is_flag=True, default=False,
              help="suppress heartbeat lines (exit code only)")
@click.argument("tool", required=False)
@click.argument("args", nargs=-1, type=click.UNPROCESSED)
def submit_cmd(socket_path, priority, share, sets, cost, after,
               pipeline_spec, profile, follow, quiet, tool, args):
    """Submit TOOL [ARGS...] (or --pipeline SPEC) to the serve daemon.

    Example: bst submit affine-fusion -o fused.ome.zarr"""
    import os

    from ..serve import client

    after_ids = [a for spec in after for a in spec.split(",") if a]
    if pipeline_spec is not None:
        extra = ([tool] if tool else []) + list(args)
        tool = "pipeline"
        args = ["run", os.path.abspath(pipeline_spec), *extra]
    elif tool is None:
        raise click.UsageError("TOOL required (or --pipeline SPEC)")

    def on_event(rec):
        if quiet:
            return
        t = rec.get("type", rec.get("event"))
        if t == "stage.progress":
            click.echo(f"[{rec.get('job')}] {rec.get('stage')}: "
                       f"{rec.get('done')}/{rec.get('total')} "
                       f"({rec.get('rate_per_s')}/s, "
                       f"eta {rec.get('eta_s')}s)", err=True)
        elif t == "log":
            click.echo(f"[{rec.get('job')}] {rec.get('message')}", err=True)
        elif t == "start":
            click.echo(f"[{rec.get('job')}] started on slot "
                       f"{rec.get('slot')}", err=True)

    try:
        resp = client.submit(
            socket_path, tool, list(args), priority=priority, share=share,
            overrides=_parse_sets(sets), cost=cost, after=after_ids,
            profile=profile, follow=follow, on_event=on_event)
    except (OSError, RuntimeError) as e:
        raise click.ClickException(
            f"{e} — is a daemon running? start one with `bst serve`")
    if not follow:
        click.echo(resp.get("job", ""))
        return
    rc = resp.get("exit_code")
    state = resp.get("state")
    if rc is None:
        # a job cancelled while still queued never ran, so it has no
        # exit code — that is still NOT success for the submitter
        rc = 0 if state == "done" else 130
    click.echo(f"[{resp.get('job')}] {state} "
               f"(exit {rc}, {resp.get('seconds')}s, "
               f"warm compile hits: {resp.get('warm_compile_hits', 0)})",
               err=True)
    if rc:
        sys.exit(int(rc))


@click.command()
@_socket_opt
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable daemon status + job table")
def jobs_cmd(socket_path, as_json):
    """List the daemon's jobs (queued, running, finished) + cache warmth."""
    from ..serve import client

    try:
        resp = client.list_jobs(socket_path)
    except (OSError, RuntimeError) as e:
        raise click.ClickException(
            f"{e} — is a daemon running? start one with `bst serve`")
    if as_json:
        click.echo(_json.dumps(resp, indent=1))
        return
    d = resp["daemon"]
    cc = d.get("chunk_cache", {})
    cf = d.get("compiled_fn", {})
    click.echo(f"daemon pid {d.get('pid')} uptime {d.get('uptime_s')}s "
               f"slots {d.get('slots')} queued {d.get('queue_depth')} "
               f"active {d.get('active')}")
    click.echo(f"caches: {cc.get('entries', 0)} chunks "
               f"({cc.get('bytes', 0)} B, {cc.get('hits', 0)} hits) | "
               f"compiled-fn warm {cf.get('warm_hits', 0)} / "
               f"cold {cf.get('cold_builds', 0)}")
    disk = cc.get("disk") or {}
    pf = cc.get("prefetch") or {}
    if disk.get("entries") or pf.get("hits") or pf.get("misses"):
        # tiered-IO warmth: the gateway's cache-affinity routing picks
        # daemons by exactly these ratios
        looked = (pf.get("hits", 0) or 0) + (pf.get("misses", 0) or 0)
        ratio = (f"{(pf.get('hits', 0) or 0) / looked * 100:.0f}%"
                 if looked else "-")
        click.echo(f"tiers: disk {disk.get('entries', 0)} chunks "
                   f"({disk.get('bytes', 0)} B, "
                   f"{disk.get('hit_bytes', 0)} B served) | "
                   f"prefetch {ratio} hit "
                   f"({pf.get('hit_bytes', 0)} B served)")
    for j in resp["jobs"]:
        line = (f"{j['id']:>6}  {j['state']:<10} {j['tool']:<24} "
                f"prio {j['priority']} share {j['share']} "
                f"wait {j['wait_s']}s")
        if "seconds" in j:
            line += f" run {j['seconds']}s"
        if j.get("exit_code") is not None:
            line += f" exit {j['exit_code']}"
        if j.get("stalled"):
            line += f" STALLED {j.get('stalled_for_s', '?')}s"
        if j.get("waiting_on"):
            line += f" after {','.join(j['waiting_on'])}"
        click.echo(line)


@click.command()
@_socket_opt
@click.argument("job_id")
def cancel_cmd(socket_path, job_id):
    """Cancel a queued or running job (running jobs unwind at the work
    loops' safe points; other jobs and the daemon are untouched)."""
    from ..serve import client

    try:
        resp = client.cancel(socket_path, job_id)
    except (OSError, RuntimeError) as e:
        raise click.ClickException(str(e))
    click.echo(f"{resp.get('job')}: {resp.get('state')}")

"""``solver`` CLI: global optimization of view registrations
(reference: Solver.java:104-158 options + AbstractRegistration.java:62-77)."""

from __future__ import annotations

import click
import numpy as np

from ..io.spimdata import ViewId
from ..models import solver as S
from ..ops import models as M
from .common import (
    infrastructure_options,
    load_project,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("-s", "--sourcePoints", "source", required=True,
              type=click.Choice(["IP", "STITCHING"], case_sensitive=False),
              help="source of the solve: IP (interest points) or STITCHING")
@click.option("-l", "--label", "labels", multiple=True,
              help="interest-point label(s) used for registration")
@click.option("-lw", "--labelweights", "label_weights", multiple=True, type=float,
              help="weight per label (default 1.0)")
@click.option("--method", default="ONE_ROUND_SIMPLE",
              type=click.Choice(["ONE_ROUND_SIMPLE", "ONE_ROUND_ITERATIVE",
                                 "TWO_ROUND_SIMPLE", "TWO_ROUND_ITERATIVE"]),
              help="two-round handles unconnected tiles, iterative drops wrong links")
@click.option("-tm", "--transformationModel", "model", default="TRANSLATION",
              type=click.Choice(["TRANSLATION", "RIGID", "AFFINE"]),
              help="transformation model (default TRANSLATION for stitching)")
@click.option("-rm", "--regularizationModel", "regularization", default="NONE",
              type=click.Choice(["NONE", "IDENTITY", "TRANSLATION", "RIGID", "AFFINE"]))
@click.option("--lambda", "lam", default=0.1, type=float,
              help="regularizer interpolation weight (default 0.1)")
@click.option("--maxError", "max_error", default=5.0, type=float)
@click.option("--maxIterations", "max_iterations", default=10000, type=int)
@click.option("--maxPlateauwidth", "max_plateau_width", default=200, type=int)
@click.option("--relativeThreshold", "relative_threshold", default=3.5, type=float)
@click.option("--absoluteThreshold", "absolute_threshold", default=7.0, type=float)
@click.option("--disableFixedViews", "disable_fixed_views", is_flag=True)
@click.option("-fv", "--fixedViews", "fixed_views", multiple=True,
              help="fixed view ids 'timepoint,setup' (default: first per subset)")
@click.option("--groupIllums/--no-groupIllums", "group_illums", default=None)
@click.option("--groupChannels/--no-groupChannels", "group_channels", default=None)
@click.option("--groupTiles", "group_tiles", is_flag=True)
@click.option("--splitTimepoints", "split_timepoints", is_flag=True)
@click.option("--solverBackend", "backend", default="auto",
              type=click.Choice(["auto", "device", "numpy"]),
              help="global-optimization backend: device = jit-compiled "
                   "lax.while_loop relaxation (sharded over local devices "
                   "above BST_SOLVE_SHARD rows), numpy = host reference "
                   "path, auto = the BST_SOLVE_DEVICE knob (default on)")
def solver_cmd(xml, dry_run, source, labels, label_weights, method, model,
               regularization, lam, max_error, max_iterations,
               max_plateau_width, relative_threshold, absolute_threshold,
               disable_fixed_views, fixed_views, group_illums, group_channels,
               group_tiles, split_timepoints, backend, **kwargs):
    """Globally optimize per-view transforms from stitching shifts or
    corresponding interest points; writes the result into the XML."""
    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kwargs)
    params = S.SolverParams(
        source=source.upper(),
        method=method,
        model=model,
        regularization=regularization,
        lam=lam,
        max_error=max_error,
        max_iterations=max_iterations,
        max_plateau_width=max_plateau_width,
        relative_threshold=relative_threshold,
        absolute_threshold=absolute_threshold,
        disable_fixed_views=disable_fixed_views,
        fixed_views=[ViewId(*map(int, fv.split(","))) for fv in fixed_views],
        labels=list(labels),
        label_weights=list(label_weights),
        group_illums=group_illums,
        group_channels=group_channels,
        group_tiles=group_tiles,
        split_timepoints=split_timepoints,
        backend=None if backend == "auto" else backend,
    )
    result = S.solve(sd, views, params)
    for key, corr in sorted(result.corrections.items()):
        click.echo(f"  {key[0]}{'+' + str(len(key) - 1) if len(key) > 1 else ''}: "
              f"t={np.round(corr[:, 3], 3)}")
    if dry_run:
        click.echo("dryRun: not saving XML")
        return
    S.store_corrections(sd, result, params)
    sd.save()
    click.echo(f"saved {xml}")

"""`bst tune` — the telemetry loop's closing arc.

advise: recorded evidence -> structured knob diagnoses.
run:    diagnoses -> coordinate-descent trials -> a tuned profile.
list/show/apply: browse the profile store; replay a winner ad hoc.

The daemon side of the loop lives in serve/ (`bst submit --profile
auto`); this module is the operator-facing face.
"""

from __future__ import annotations

import json as _json
import os

import click

from .observe_tools import _history_dir_opt


@click.group("tune")
def tune_cmd():
    """History-driven performance advisor + knob autotuner."""


@tune_cmd.command("advise")
@_history_dir_opt
@click.option("--trace", "trace", default=None,
              type=click.Path(exists=True),
              help="trace file or telemetry dir to decompose (default: "
                   "the record's own trace_file pointer, when reachable)")
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable diagnoses")
@click.argument("ref", required=False, default="-1")
def tune_advise_cmd(history_dir, trace, as_json, ref):
    """Run the advisor rules over one recorded run.

    REF is a history record (id, unique prefix, a negative index via
    `-- -2`; default: the latest record) or a path to a manifest/record
    JSON file. Each fired rule names the evidence, the implicated knob
    and a suggested value."""
    from .. import tune

    try:
        diags, rec = tune.advise(ref, history_dir=history_dir,
                                 trace=trace)
    except (FileNotFoundError, KeyError) as e:
        raise click.ClickException(str(e))
    if as_json:
        click.echo(_json.dumps(
            {"run": rec.get("id") or rec.get("tool"),
             "tool": rec.get("tool"),
             "diagnoses": [d.as_dict() for d in diags]},
            indent=1, default=str))
    else:
        click.echo(tune.render(diags, rec))


@tune_cmd.command("run")
@_history_dir_opt
@click.option("--workload", default="tiny-fusion", show_default=True,
              help="'tiny-fusion' (the built-in CPU-fallback bench "
                   "workload) or a `bst pipeline` spec path")
@click.option("--workdir", default=None, type=click.Path(file_okay=False),
              help="working directory for workload fixtures + per-trial "
                   "telemetry (default: <history-dir>/tune-work)")
@click.option("--trials", type=int, default=2, show_default=True,
              help="best-of-N timed executions per configuration")
@click.option("--max-trials", type=int, default=12, show_default=True,
              help="hard cap on total timed executions")
@click.option("--min-gain", type=float, default=0.02, show_default=True,
              help="fractional improvement a candidate must show to "
                   "displace the incumbent (noise floor)")
@click.option("--knob", "knobs", multiple=True,
              help="force this tunable knob into the search even when "
                   "no advisor rule implicates it (repeatable)")
@click.option("--no-warmup", is_flag=True, default=False,
              help="skip the untimed warmup execution")
@click.option("--no-save", is_flag=True, default=False,
              help="measure but do not persist a profile")
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable tune result")
def tune_run_cmd(history_dir, workload, workdir, trials, max_trials,
                 min_gain, knobs, no_warmup, no_save, as_json):
    """Autotune: baseline the workload, advise on its record, then
    hill-climb each implicated knob under config.overrides() — every
    trial lands in the history store (tool `tune-trial`, diffable with
    `bst perf-diff --tool tune-trial`) and the winner persists as a
    profile for this backend/device-count/shape."""
    from .. import tune
    from ..observe import history

    hist = history.history_dir(history_dir)
    if hist is None:
        raise click.ClickException(
            "tune run needs a history store for trials + profiles: set "
            "BST_HISTORY_DIR or pass --history-dir")
    from .. import config

    for k in knobs:
        if k not in config.tunable_knobs():
            raise click.ClickException(
                f"--knob {k}: not a declared-tunable knob (see "
                f"`bst config --json` for tunable metadata)")
    try:
        wl = tune.resolve_workload(
            workload, workdir or os.path.join(hist, "tune-work"))
    except (ValueError, FileNotFoundError) as e:
        raise click.ClickException(str(e))
    result = tune.autotune(
        wl, force_knobs=knobs, trials_per_config=trials,
        max_trials=max_trials, min_gain=min_gain, history_dir=hist,
        workdir=workdir, warmup=not no_warmup, save=not no_save)
    if as_json:
        click.echo(_json.dumps(result.as_dict(), indent=1, default=str))
        return
    click.echo(f"workload {result.workload} ({result.shape}) on "
               f"{result.backend}/{result.device_count}dev: "
               f"{len(result.trials)} trial(s)")
    for d in result.diagnoses:
        click.echo(f"  rule {d.rule} -> "
                   + (f"{d.knob}={d.suggested_value}" if d.knob
                      else "(no knob)"))
    click.echo(f"baseline {result.baseline_seconds:.3f}s -> best "
               f"{result.best_seconds:.3f}s "
               f"({result.baseline_seconds / result.best_seconds:.2f}x)"
               if result.best_seconds else "no successful trials")
    if result.best_overrides:
        for k, v in sorted(result.best_overrides.items()):
            click.echo(f"  {k}={v}")
    else:
        click.echo("  default configuration wins (empty override set)")
    if result.profile_key:
        click.echo(f"profile saved: {result.profile_key}")


def _load_store_or_die(history_dir):
    from .. import tune

    try:
        return tune.load_store(history_dir)
    except FileNotFoundError as e:
        raise click.ClickException(str(e))


def _resolve_profile_or_die(store, ref):
    from .. import tune

    try:
        if ref == "auto":
            backend, ndev = tune.backend_signature()
            prof = tune.match_profile(store, backend=backend,
                                      device_count=ndev, ref="auto")
        else:
            prof = tune.match_profile(store, backend="", device_count=0,
                                      ref=ref)
    except KeyError as e:
        raise click.ClickException(str(e))
    if prof is None:
        raise click.ClickException(
            f"no profile matching {ref!r} (run `bst tune run` first; "
            f"`bst tune list` shows the store)")
    return prof


@tune_cmd.command("list")
@_history_dir_opt
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable profile store")
def tune_list_cmd(history_dir, as_json):
    """List stored tuned profiles."""
    store = _load_store_or_die(history_dir)
    profs = store.get("profiles") or {}
    if as_json:
        click.echo(_json.dumps(store, indent=1, default=str))
        return
    if not profs:
        click.echo("no profiles stored (run `bst tune run`)")
        return
    for key in sorted(profs):
        p = profs[key]
        n_ov = len(p.get("overrides") or {})
        click.echo(f"{key:<40} {p.get('workload', '?'):<14} "
                   f"{p.get('speedup', '?')}x  {n_ov} override(s)  "
                   f"{p.get('created_at', '?')}")


@tune_cmd.command("show")
@_history_dir_opt
@click.argument("ref")
def tune_show_cmd(history_dir, ref):
    """Print one profile (by key, unique key prefix, or `auto` for the
    best match on this host)."""
    store = _load_store_or_die(history_dir)
    prof = _resolve_profile_or_die(store, ref)
    click.echo(_json.dumps(prof, indent=1, default=str))


@tune_cmd.command("apply",
                  context_settings={"ignore_unknown_options": True,
                                    "allow_interspersed_args": False})
@_history_dir_opt
@click.option("--json", "as_json", is_flag=True,
              help="machine-readable override set")
@click.argument("ref")
@click.argument("tool", required=False)
@click.argument("args", nargs=-1, type=click.UNPROCESSED)
def tune_apply_cmd(history_dir, as_json, ref, tool, args):
    """Apply a stored profile: print its override set, or — given a
    trailing TOOL [ARGS...] — execute that tool in-process under the
    profile's config.overrides() scope (the ad-hoc spelling of what
    `bst submit --profile` does through the daemon). Options for `tune
    apply` itself go BEFORE the profile ref; everything after TOOL is
    passed through verbatim."""
    from .. import config, tune

    store = _load_store_or_die(history_dir)
    prof = _resolve_profile_or_die(store, ref)
    ov = prof.get("overrides") or {}
    if tool:
        from ..tune.workloads import _invoke_cli

        try:
            with config.overrides(ov):
                _invoke_cli([tool, *args])
        except (KeyError, RuntimeError) as e:
            raise click.ClickException(str(e))
        return
    if as_json:
        click.echo(_json.dumps({"key": prof["key"], "overrides": ov},
                               indent=1, default=str))
        return
    click.echo(f"# profile {prof['key']} "
               f"(baseline {prof.get('baseline_seconds')}s -> best "
               f"{prof.get('best_seconds')}s)")
    if not ov:
        click.echo("# empty override set: the default configuration won")
    for k, v in sorted(ov.items()):
        click.echo(f"{k}={v}")

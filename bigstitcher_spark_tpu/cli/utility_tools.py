"""CLI: clear-interestpoints, clear-registrations, transform-points,
split-images (reference tools ClearInterestPoints.java, ClearRegistrations.java,
TransformPoints.java, SplitDatasets.java)."""

from __future__ import annotations

import click
import numpy as np

from .common import (
    infrastructure_options,
    load_project,
    parse_csv_ints,
    select_views_from_kwargs,
    view_selection_options,
    xml_option,
)


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("-l", "--label", default=None,
              help="only this interest point label (default: all labels)")
@click.option("--correspondencesOnly", "--onlyCorrespondences", "only_corrs",
              is_flag=True,
              help="delete only correspondences, keep the points")
def clear_interestpoints_cmd(xml, dry_run, label, only_corrs, **kw):
    """Delete interest points (or only correspondences) from XML + store
    (ClearInterestPoints.java:92-117)."""
    from ..io.interestpoints import InterestPointStore

    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    store = InterestPointStore.for_project(sd)
    n = 0
    for v in views:
        labels = ([label] if label else list(sd.interest_points.get(v, {})))
        for lab in labels:
            if lab not in sd.interest_points.get(v, {}):
                continue
            if dry_run:
                click.echo(f"would clear {v} label {lab!r}")
                continue
            if only_corrs:
                store.clear_correspondences(v, lab)
            else:
                store.remove_view(v, lab)
                del sd.interest_points[v][lab]
                if not sd.interest_points[v]:
                    del sd.interest_points[v]
            n += 1
    what = "correspondences" if only_corrs else "interest points"
    click.echo(f"cleared {what} of {n} (view, label) entries")
    if not dry_run:
        sd.save(xml)


@click.command()
@xml_option
@view_selection_options
@infrastructure_options
@click.option("--keep", type=int, default=None,
              help="keep only the first N transformations "
                   "(in order of application: calibration first)")
@click.option("--remove", type=int, default=None,
              help="remove the last N transformations (the most recent)")
def clear_registrations_cmd(xml, dry_run, keep, remove, **kw):
    """Remove view transforms from the XML (ClearRegistrations.java:74-101).

    The chain is stored outermost-first: list index 0 is the LAST-applied
    transform, so --remove pops from the front and --keep pops the front
    until N remain."""
    if (keep is None) == (remove is None) or (keep or 0) < 0 or (remove or 0) < 0:
        raise click.ClickException("specify exactly one of --keep / --remove, >= 0")
    sd = load_project(xml)
    views = select_views_from_kwargs(sd, kw)
    for v in views:
        chain = sd.registrations.get(v)
        if not chain:
            continue
        if remove is not None:
            drop = chain[: min(remove, len(chain))]
        else:
            drop = chain[: max(len(chain) - keep, 0)]
        for t in drop:
            click.echo(f"{v}: removing {t.name!r}")
        sd.registrations[v] = chain[len(drop):]
    if not dry_run:
        sd.save(xml)
        click.echo("saved XML")


@click.command()
@xml_option
@infrastructure_options
@click.option("-vi", "vi", required=True,
              help="view 'timepoint,setup' whose transform chain to apply")
@click.option("-p", "--point", "points", multiple=True,
              help="input point 'x,y,z' (repeatable)")
@click.option("--csvIn", "csv_in", default=None, type=click.Path(exists=True),
              help="CSV file with x,y,z rows")
@click.option("--csvOut", "csv_out", default=None,
              help="write transformed points to this CSV instead of stdout")
def transform_points_cmd(xml, dry_run, vi, points, csv_in, csv_out):
    """Apply a view's full pixel->world affine chain to 3-D points
    (TransformPoints.java:71-134)."""
    from ..io.spimdata import ViewId
    from ..utils.geometry import apply_affine

    sd = load_project(xml)
    tp, setup = (int(v) for v in vi.split(","))
    view = ViewId(tp, setup)
    if view not in sd.registrations:
        raise click.ClickException(f"view {view} has no registration")
    pts = []
    for p in points:
        pts.append([float(v) for v in p.split(",")])
    if csv_in:
        with open(csv_in) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                pts.append([float(v) for v in line.replace(";", ",").split(",")[:3]])
    if not pts:
        raise click.ClickException("no points given (-p or --csvIn)")
    out = apply_affine(sd.model(view), np.asarray(pts, np.float64))
    lines = [",".join(repr(float(v)) for v in row) for row in out]
    if csv_out and not dry_run:
        with open(csv_out, "w") as f:
            f.write("\n".join(lines) + "\n")
        click.echo(f"wrote {len(lines)} transformed points to {csv_out}")
    else:
        for src, dst in zip(pts, lines):
            click.echo(f"{tuple(src)} -> {dst}")


@click.command()
@xml_option
@infrastructure_options
@click.option("-xo", "--xmlout", "xml_out", default=None,
              help="output XML (default: overwrite input)")
@click.option("-tis", "--targetImageSize", "-s", "--targetSize",
              "target_size", default="4000,4000,2000",
              help="target sub-image size x,y,z (SplitDatasets defaults)")
@click.option("-to", "-o", "--targetOverlap", "target_overlap",
              default="200,200,100",
              help="target sub-image overlap x,y,z")
@click.option("--disableOptimization", "disable_optimization", is_flag=True,
              help="use the target size/overlap exactly instead of the "
                   "closest larger divisible-by-downsampling sizes")
@click.option("--assignIlluminations", "assign_illums", is_flag=True,
              help="store old tile ids as illumination ids")
@click.option("-fip", "--fakeInterestPoints", "fake_ips", is_flag=True,
              help="plant corresponding fake points in split overlaps")
@click.option("--fipDensity", "fip_density", type=float, default=100.0)
@click.option("--fipMinNumPoints", "fip_min", type=int, default=20)
@click.option("--fipMaxNumPoints", "fip_max", type=int, default=500)
@click.option("--fipError", "fip_error", type=float, default=0.5)
@click.option("--fipExclusionRadius", "fip_exclusion_radius", type=float,
              default=20.0,
              help="minimum distance between planted fake points")
@click.option("--displayResult", "display_result", is_flag=True,
              help="GUI preview is unavailable headless: prints the split "
                   "layout instead")
def split_images_cmd(xml, dry_run, xml_out, target_size, target_overlap,
                     disable_optimization, assign_illums, fake_ips,
                     fip_density, fip_min, fip_max, fip_error,
                     fip_exclusion_radius, display_result):
    """Virtually split large tiles into overlapping sub-tiles
    (SplitDatasets / SplittingTools.splitImages)."""
    from ..io.dataset_io import ViewLoader
    from ..io.interestpoints import InterestPointStore
    from ..models.splitting import split_images

    sd = load_project(xml)
    loader = ViewLoader(sd)
    store = InterestPointStore.for_project(sd) if fake_ips else None
    new_sd = split_images(
        sd, loader,
        tuple(parse_csv_ints(target_size, 3)),
        tuple(parse_csv_ints(target_overlap, 3)),
        assign_illuminations=assign_illums,
        fake_interest_points=fake_ips,
        fip_density=fip_density, fip_min=fip_min, fip_max=fip_max,
        fip_error=fip_error, fip_store=store,
        fip_exclusion_radius=fip_exclusion_radius,
        optimize=not disable_optimization,
    )
    if display_result:
        for sid in sorted(new_sd.setups):
            su = new_sd.setups[sid]
            src = new_sd.split_info.get(sid)
            click.echo(f"  setup {sid}: size {su.size}"
                  + (f" <- source setup {src[0]} @ offset {tuple(src[1])}"
                     if src is not None else ""))
    click.echo(f"split {len(sd.setups)} setups into {len(new_sd.setups)} sub-views")
    if dry_run:
        click.echo("dryRun: not saving")
        return
    out = xml_out or xml
    new_sd.save(out)
    click.echo(f"saved {out}")


@click.command()
@xml_option
@click.option("-vi", "vi", multiple=True,
              help="restrict to view ids 'timepoint,setup' (repeatable)")
@click.option("-l", "--label", "labels", multiple=True,
              help="restrict to these labels")
def inspect_interestpoints_cmd(xml, vi, labels):
    """Print the interestpoints.n5 layout: per (view, label) the point/
    correspondence datasets, counts, and parameters (debug printer role of
    SpimData2Util.java:49-162)."""
    import numpy as np

    from ..io.interestpoints import InterestPointStore, view_group
    from ..io.spimdata import SpimData, ViewId

    import os

    sd = SpimData.load(xml)
    root = os.path.join(os.path.dirname(sd.xml_path or "."),
                        "interestpoints.n5")
    if not os.path.isdir(root):
        click.echo(f"no interestpoints store at {root}")
        return
    store = InterestPointStore(root)
    click.echo(f"interestpoints store: {store.root}")
    views = sorted(sd.interest_points)
    if vi:
        want = {ViewId(*(int(x) for x in v.split(","))) for v in vi}
        views = [v for v in views if v in want]
    total_p = total_c = 0
    for v in views:
        for label, lk in sorted(sd.interest_points.get(v, {}).items()):
            if labels and label not in labels:
                continue
            grp = view_group(v, label)
            ids, locs = store.load_points(v, label)
            corrs = store.load_correspondences(v, label)
            total_p += len(ids)
            total_c += len(corrs)
            click.echo(f"{v} label '{label}' ({grp}):")
            click.echo(f"  interestpoints: {len(ids)} points"
                       + (f", loc dims {locs.shape[1]}" if len(ids) else ""))
            if len(ids):
                mn = np.min(locs, axis=0)
                mx = np.max(locs, axis=0)
                click.echo(f"  bounds: {mn.round(1).tolist()} -> "
                           f"{mx.round(1).tolist()}")
            if lk.params:
                click.echo(f"  parameters: {lk.params}")
            by_other = {}
            for c in corrs:
                key = (c.other_view, c.other_label)
                by_other[key] = by_other.get(key, 0) + 1
            click.echo(f"  correspondences: {len(corrs)} total")
            for (ov, ol), n in sorted(by_other.items(),
                                      key=lambda kv: str(kv[0])):
                click.echo(f"    -> {ov} '{ol}': {n}")
    click.echo(f"TOTAL: {total_p} points, {total_c} correspondences "
               f"in {len(views)} views")


@click.command()
@xml_option
@infrastructure_options
@click.option("-xo", "--xmlout", "xml_out", default=None,
              help="output XML (default: overwrite input)")
@click.option("--rows", type=int, required=True,
              help="tile grid row count")
@click.option("--columns", type=int, required=True,
              help="tile grid column count")
@click.option("--parallelRows", "parallel_rows", type=int, default=4,
              help="rows acquired in parallel (mirror scope sets)")
def map_setup_ids_cmd(xml, dry_run, xml_out, rows, columns, parallel_rows):
    """Remap ViewSetup ids to acquisition order for parallel-row mirror
    scopes (SetupIDMapper.java:36-107: grid ids run bottom-right row-first;
    acquisition completes every parallelRows-th row right-to-left first)."""
    from ..io.spimdata import SpimData
    from ..utils.viewselect import keller_mirror_scope_map

    sd = SpimData.load(xml)
    mapping = keller_mirror_scope_map(rows, columns, parallel_rows)
    if set(mapping) != set(sd.setups):
        raise click.ClickException(
            f"grid {rows}x{columns} needs setups {min(mapping)}..{max(mapping)}; "
            f"XML has {sorted(sd.setups)[:3]}..{sorted(sd.setups)[-3:]}")
    for old in sorted(mapping):
        click.echo(f"  setup {old} -> {mapping[old]}")
    if dry_run:
        return
    try:
        sd.remap_setup_ids(mapping)
    except ValueError as e:
        raise click.ClickException(str(e)) from e
    sd.save(xml_out or xml)
    click.echo(f"remapped {len(mapping)} setups -> {xml_out or xml}")


@click.command()
def env_cmd():
    """Print runtime diagnostics: devices, native codec, storage config
    (the role of the reference's Spark/executor-identity printouts,
    util/Spark.java:235-238 / cloud/TestCloudFunctions.java)."""
    import jax

    import bigstitcher_spark_tpu
    from ..io import native_blockio, uris
    from ..parallel.distributed import world

    click.echo(f"bigstitcher_spark_tpu {getattr(bigstitcher_spark_tpu, '__version__', 'dev')}")
    click.echo(f"jax {jax.__version__}")
    try:
        devs = jax.local_devices()
        pi, pc = world()
        click.echo(f"backend: {jax.default_backend()}; "
                   f"{len(devs)} local device(s): "
                   f"{', '.join(str(d) for d in devs)}")
        click.echo(f"process {pi} of {pc}"
                   + (" (multi-host runtime active)" if pc > 1 else ""))
    except Exception as e:  # a dead accelerator tunnel must not hide the rest
        click.echo(f"backend: UNAVAILABLE ({e})")
    import tensorstore as ts

    ts_ver = getattr(ts, "__version__", None)
    click.echo(f"tensorstore {ts_ver or '(version attribute unavailable)'}")
    if native_blockio.available():
        click.echo(
            "native codec: available"
            + (", zarr" if native_blockio.has_zarr() else "")
            + (", lz4" if native_blockio.has_lz4() else ", no-lz4")
            + (", fused-region-read" if native_blockio.has_region_read()
               else ", whole-block-read"))
    else:
        click.echo("native codec: NOT built (make -C native; "
                   "tensorstore fallback active, lz4 N5 unreadable)")
    # the full resolved knob surface (defaults vs env overrides) instead
    # of the single raw BST_NATIVE_IO echo this used to print — `bst
    # config -v` adds per-knob docs
    from .. import config

    click.echo("runtime config (bst config -v for docs; (env) = overridden):")
    for line in config.describe().splitlines():
        click.echo(f"  {line}")
    if uris.get_s3_region():
        click.echo(f"s3 region: {uris.get_s3_region()}")
    if uris.get_s3_endpoint():
        click.echo(f"s3 endpoint: {uris.get_s3_endpoint()}")


def make_container_server(root: str, port: int = 0):
    """HTTP server over a local container directory with CORS headers
    (browser viewers — neuroglancer in particular — refuse cross-origin
    chunk fetches without Access-Control-Allow-Origin). port=0 binds an
    ephemeral port; the caller reads ``server_address``."""
    import functools
    import http.server

    class Handler(http.server.SimpleHTTPRequestHandler):
        def end_headers(self):
            self.send_header("Access-Control-Allow-Origin", "*")
            super().end_headers()

        def log_message(self, *args):  # keep the CLI output readable
            pass

    return http.server.ThreadingHTTPServer(
        ("127.0.0.1", port), functools.partial(Handler, directory=root))


@click.command()
@click.argument("container", type=click.Path(exists=True, file_okay=False))
@click.option("--port", type=int, default=8399, show_default=True,
              help="listen port (0 picks a free one)")
def serve_container_cmd(container, port):
    """Serve a local fusion container over HTTP for interactive preview —
    the headless counterpart of the reference's --displayResult BDV window
    (SplitDatasets.java:131) and GUI loading probe
    (cloud/TestN5Loading.java:115-143). Open the printed source in
    neuroglancer, or point BigDataViewer/Fiji (Open N5/OME-ZARR via URL)
    at the served address."""
    import os

    srv = make_container_server(container, port)
    host, p = srv.server_address
    fmt = ("n5" if os.path.exists(os.path.join(container, "attributes.json"))
           else "zarr")
    click.echo(f"serving {container} at http://{host}:{p}/ (CORS enabled)")
    click.echo(f"neuroglancer source: {fmt}://http://{host}:{p}/<dataset>")
    click.echo("BigDataViewer/Fiji: Plugins > BigDataViewer > "
               f"Open N5/OME-ZARR -> http://{host}:{p}/")
    click.echo("Ctrl-C to stop")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
